"""Attack zoo — adversarial client behaviors used to *test* defenses.

Parity target: reference ``core/security/attack/`` (byzantine, label-flip,
backdoor/model-replacement, DLG / invert-gradient) with the
``FedMLAttacker`` singleton dispatch (``core/security/fedml_attacker.py``).
Attacks here are pure transforms on either the stacked update matrix
(model-poisoning) or on client data arrays (data-poisoning), so simulations
can inject them inside jit.
"""

from __future__ import annotations

from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ....utils.confval import get_float, get_int

PyTree = Any

ATTACK_TYPES = ("byzantine_random", "byzantine_zero", "byzantine_flip",
                "label_flip", "model_replacement", "gaussian_noise",
                "backdoor", "edge_case_backdoor", "lazy_worker")


# --- model poisoning (operate on [K, D] update matrix + byzantine mask) ----

def byzantine_random(mat: jnp.ndarray, byz_mask: jnp.ndarray,
                     rng: jax.Array, scale: float = 1.0) -> jnp.ndarray:
    """Replace byzantine clients' updates with gaussian noise (reference
    ``attack/byzantine_attack.py`` mode 'random')."""
    noise = scale * jax.random.normal(rng, mat.shape)
    return jnp.where(byz_mask[:, None] > 0, noise, mat)


def byzantine_zero(mat: jnp.ndarray, byz_mask: jnp.ndarray) -> jnp.ndarray:
    return jnp.where(byz_mask[:, None] > 0, jnp.zeros_like(mat), mat)


def byzantine_flip(mat: jnp.ndarray, byz_mask: jnp.ndarray,
                   scale: float = 1.0) -> jnp.ndarray:
    """Sign-flip (inner-product manipulation) attack."""
    return jnp.where(byz_mask[:, None] > 0, -scale * mat, mat)


def model_replacement(mat: jnp.ndarray, byz_mask: jnp.ndarray,
                      boost: float) -> jnp.ndarray:
    """Backdoor model-replacement boosting (reference
    ``attack/backdoor_attack.py``): attacker scales its update by ~K so the
    average equals its target model."""
    return jnp.where(byz_mask[:, None] > 0, boost * mat, mat)


def gaussian_noise(mat: jnp.ndarray, rng: jax.Array,
                   stddev: float = 0.1) -> jnp.ndarray:
    """Additive noise on every update (untargeted degradation)."""
    return mat + stddev * jax.random.normal(rng, mat.shape)


# --- data poisoning --------------------------------------------------------

def lazy_worker(mat: jnp.ndarray, byz_mask: jnp.ndarray, rng: jax.Array,
                noise: float = 1e-3) -> jnp.ndarray:
    """Freeloaders (reference lazy-worker attack): byzantine clients do no
    training and submit a near-zero update with a dash of noise to evade
    exact-zero detection."""
    fake = noise * jax.random.normal(rng, mat.shape, mat.dtype)
    m = byz_mask.reshape(-1, 1).astype(mat.dtype)
    return mat * (1 - m) + fake * m


def backdoor_stamp(x: np.ndarray, trigger_value: float = 1.0,
                   patch: int = 3, image: Optional[bool] = None
                   ) -> np.ndarray:
    """Stamp the backdoor trigger (a corner patch) onto samples.

    ``image=True`` stamps a top-left ``patch x patch`` corner on
    [..., H, W, C] layouts; ``image=False`` stamps the first
    ``patch * patch`` features of flat [..., F] layouts. Leading axes are
    arbitrary (batched/stacked inputs), so callers that know the layout
    MUST pass ``image`` — the ndim heuristic only covers the unbatched
    2D/4D cases."""
    x = np.array(x, copy=True)
    if image is None:
        image = x.ndim >= 3
    if image:
        x[..., :patch, :patch, :] = trigger_value
    else:
        x[..., :patch * patch] = trigger_value
    return x


def label_flip(y: np.ndarray, num_classes: int,
               src: Optional[int] = None, dst: Optional[int] = None
               ) -> np.ndarray:
    """Label-flipping (reference ``attack/label_flipping_attack.py``):
    src->dst targeted flip, or y -> C-1-y untargeted when src is None."""
    y = np.asarray(y)
    if src is None:
        return (num_classes - 1 - y).astype(y.dtype)
    out = y.copy()
    out[y == src] = dst if dst is not None else (num_classes - 1 - src)
    return out


class FedMLAttacker:
    """Singleton dispatch (reference ``fedml_attacker.py``): engines consult
    it to poison data before training and updates before aggregation."""

    _instance = None

    def __init__(self, args):
        self.args = args
        self.attack_type = str(getattr(args, "attack_type", None) or "").lower()
        self.enabled = bool(getattr(args, "enable_attack", False)) and \
            self.attack_type in ATTACK_TYPES
        self.byzantine_client_num = get_int(args, "byzantine_client_num", 0)
        self.attack_scale = get_float(args, "attack_scale", 1.0)

    @classmethod
    def get_instance(cls, args=None) -> "FedMLAttacker":
        if args is not None or cls._instance is None:
            cls._instance = cls(args)
        return cls._instance

    def is_model_attack(self) -> bool:
        return self.enabled and self.attack_type in (
            "byzantine_random", "byzantine_zero", "byzantine_flip",
            "model_replacement", "gaussian_noise", "lazy_worker")

    def is_data_attack(self) -> bool:
        return self.enabled and self.attack_type in (
            "label_flip", "backdoor", "edge_case_backdoor")

    def byzantine_mask(self, client_ids: np.ndarray) -> np.ndarray:
        """Clients 0..f-1 are byzantine (deterministic, test-friendly)."""
        return (np.asarray(client_ids) < self.byzantine_client_num
                ).astype(np.float32)

    def poison_updates(self, mat: jnp.ndarray, client_ids: np.ndarray,
                       rng: jax.Array) -> jnp.ndarray:
        mask = jnp.asarray(self.byzantine_mask(client_ids))
        t = self.attack_type
        if t == "byzantine_random":
            return byzantine_random(mat, mask, rng, self.attack_scale)
        if t == "byzantine_zero":
            return byzantine_zero(mat, mask)
        if t == "byzantine_flip":
            return byzantine_flip(mat, mask, self.attack_scale)
        if t == "model_replacement":
            boost = self.attack_scale if self.attack_scale != 1.0 else float(
                mat.shape[0])
            return model_replacement(mat, mask, boost)
        if t == "gaussian_noise":
            return gaussian_noise(mat, rng, self.attack_scale)
        if t == "lazy_worker":
            return lazy_worker(mat, mask, rng)
        return mat

    def poison_labels(self, y: np.ndarray, num_classes: int) -> np.ndarray:
        return label_flip(y, num_classes)
