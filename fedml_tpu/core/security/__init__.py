"""Trust & robustness cross-cuts (reference ``core/security/``): attack zoo,
defense dispatch, gradient-inversion demo. Engines consult the
``FedMLAttacker`` / ``FedMLDefender`` singletons exactly where the reference
consults them from the ClientTrainer/ServerAggregator hooks."""

from .attack import FedMLAttacker, ATTACK_TYPES
from .defense import FedMLDefender, DEFENSE_TYPES, stack_to_matrix
from .defense import robust_agg

__all__ = ["FedMLAttacker", "FedMLDefender", "ATTACK_TYPES",
           "DEFENSE_TYPES", "stack_to_matrix", "robust_agg"]
