"""Robust aggregation kernels — Byzantine-tolerant alternatives to FedAvg.

Parity target: the reference's defense zoo (``core/security/defense/`` — 22
defenses dispatched by ``fedml_defender.py:55-116``). The reference
implements them as loops over state-dicts of torch tensors; here each defense
is a pure jit-able function over ``(updates, weights)`` where ``updates`` is
the [K, D] matrix of flattened client updates — so a robust round can run as
one XLA program (on the mesh engine the [K, D] matrix arrives via
``all_gather`` instead of the psum fast path).

All functions return ``(aggregated_vector [D], info dict)``.
"""

from __future__ import annotations

from typing import Dict, Tuple

import jax
import jax.numpy as jnp

Arr = jnp.ndarray


def _normalize(weights: Arr) -> Arr:
    return weights / jnp.maximum(jnp.sum(weights), 1e-12)


def weighted_mean(updates: Arr, weights: Arr) -> Arr:
    return jnp.einsum("k,kd->d", _normalize(weights), updates)


# ---------------------------------------------------------------------------
# distance / score based selection
# ---------------------------------------------------------------------------

def pairwise_sq_dists(updates: Arr) -> Arr:
    """[K, K] squared euclidean distances."""
    sq = jnp.sum(updates * updates, axis=1)
    return jnp.maximum(sq[:, None] + sq[None, :]
                       - 2.0 * updates @ updates.T, 0.0)


def krum_scores_from_dists(dists: Arr, byzantine_count: int) -> Arr:
    """Krum scoring on an already-computed [K, K] squared-distance matrix —
    the ONE implementation shared with the sharded kernels (which psum the
    matrix from per-shard partials); any drift would silently break
    host/fused verdict parity for krum, multi-krum, and bulyan."""
    k = dists.shape[0]
    closest = max(k - byzantine_count - 2, 1)
    d = dists + jnp.eye(k) * 1e30  # exclude self
    sorted_d = jnp.sort(d, axis=1)
    return jnp.sum(sorted_d[:, :closest], axis=1)


def krum_scores(updates: Arr, byzantine_count: int) -> Arr:
    """Krum score per client: sum of its K - f - 2 smallest squared distances
    to other clients (Blanchard et al.; reference
    ``defense/krum_defense.py``)."""
    return krum_scores_from_dists(pairwise_sq_dists(updates),
                                  byzantine_count)


def krum(updates: Arr, weights: Arr, byzantine_count: int = 0,
         multi_k: int = 1) -> Tuple[Arr, Dict]:
    """Krum (multi_k=1) / Multi-Krum (multi_k=m): select the m lowest-score
    updates and average them."""
    scores = krum_scores(updates, byzantine_count)
    m = max(int(multi_k), 1)
    _, sel = jax.lax.top_k(-scores, m)
    sel_mask = jnp.zeros(updates.shape[0]).at[sel].set(1.0)
    w = weights * sel_mask
    return weighted_mean(updates, w), {"scores": scores, "selected": sel_mask}


def coordinate_median(updates: Arr, weights: Arr) -> Tuple[Arr, Dict]:
    """Coordinate-wise median (Yin et al.; reference
    ``defense/coordinate_wise_median_defense.py``)."""
    return jnp.median(updates, axis=0), {}


def trimmed_mean(updates: Arr, weights: Arr, trim_fraction: float = 0.1
                 ) -> Tuple[Arr, Dict]:
    """Coordinate-wise beta-trimmed mean (reference
    ``defense/coordinate_wise_trimmed_mean_defense.py``): drop the highest
    and lowest ``trim_fraction`` of values per coordinate, average the rest."""
    k = updates.shape[0]
    b = min(int(k * trim_fraction), (k - 1) // 2)
    s = jnp.sort(updates, axis=0)
    kept = s[b:k - b] if b > 0 else s
    return jnp.mean(kept, axis=0), {"trimmed_each_side": b}


def geometric_median(updates: Arr, weights: Arr, iters: int = 8,
                     eps: float = 1e-8, tol: float = 0.0) -> Tuple[Arr, Dict]:
    """RFA — smoothed Weiszfeld iteration for the weighted geometric median
    (Pillutla et al.; reference ``defense/RFA_defense.py``).

    ``tol > 0`` (the ``rfa_tol`` knob) turns the fixed trip count into a
    budget: iterate until the estimate moves less than ``tol`` (euclidean)
    or ``iters`` is exhausted, and report the count in ``info``. At the
    default ``tol = 0`` the loop is the exact fixed-trip-count kernel the
    sharded ``lax.while_loop`` is bit-parity-tested against; with a
    tolerance both kernels share the same movement rule but associate
    their float reductions differently (flat sum here, psum of per-shard
    partials there), so near the exit boundary they may differ by one
    iteration — parity then holds to the tolerance, not the bit."""
    w = _normalize(weights)

    def body(_, v):
        dist = jnp.sqrt(jnp.sum((updates - v[None]) ** 2, axis=1) + eps)
        beta = w / jnp.maximum(dist, eps)
        beta = beta / jnp.maximum(jnp.sum(beta), 1e-12)
        return jnp.einsum("k,kd->d", beta, updates)

    v0 = weighted_mean(updates, w)
    if tol <= 0.0:
        v = jax.lax.fori_loop(0, iters, body, v0)
        return v, {"iters_run": jnp.int32(iters)}

    def step(carry):
        i, v, _ = carry
        new = body(0, v)
        return i + 1, new, jnp.linalg.norm(new - v)

    def cond(carry):
        i, _, moved = carry
        return (i < iters) & (moved > tol)

    i, v, _ = jax.lax.while_loop(
        cond, step, (jnp.int32(0), v0, jnp.float32(jnp.inf)))
    return v, {"iters_run": i}


def bulyan(updates: Arr, weights: Arr, byzantine_count: int = 0
           ) -> Tuple[Arr, Dict]:
    """Bulyan (El Mhamdi et al.; reference ``defense/bulyan_defense.py``):
    iterative Multi-Krum selection of theta = K - 2f updates, then
    coordinate-wise trimmed mean keeping theta - 2f values per coordinate."""
    k = updates.shape[0]
    f = byzantine_count
    theta = max(k - 2 * f, 1)
    scores = krum_scores(updates, f)
    _, sel = jax.lax.top_k(-scores, theta)
    chosen = updates[sel]
    beta = max((theta - 2 * f), 1)
    med = jnp.median(chosen, axis=0)
    dist_to_med = jnp.abs(chosen - med[None])
    _, nearest = jax.lax.top_k(-dist_to_med.T, beta)  # [D, beta]
    vals = jnp.take_along_axis(chosen.T, nearest, axis=1)
    return jnp.mean(vals, axis=1), {"selected": sel}


# ---------------------------------------------------------------------------
# clipping / noise
# ---------------------------------------------------------------------------

def norm_clip(updates: Arr, weights: Arr, max_norm: float = 1.0
              ) -> Tuple[Arr, Dict]:
    """Norm-bounded aggregation (reference ``defense/norm_diff_clipping_defense.py``):
    scale each update to at most ``max_norm`` before weighted averaging."""
    norms = jnp.linalg.norm(updates, axis=1)
    scale = jnp.minimum(1.0, max_norm / jnp.maximum(norms, 1e-12))
    return weighted_mean(updates * scale[:, None], weights), {"norms": norms}


def centered_clip(updates: Arr, weights: Arr, tau: float = 1.0,
                  iters: int = 3, momentum: Arr = None) -> Tuple[Arr, Dict]:
    """Centered clipping (Karimireddy et al.; reference
    ``defense/cclip_defense.py``): v <- v + mean_k clip(u_k - v, tau)."""
    v = jnp.zeros(updates.shape[1]) if momentum is None else momentum
    w = _normalize(weights)

    def body(_, v):
        diff = updates - v[None]
        norms = jnp.linalg.norm(diff, axis=1)
        scale = jnp.minimum(1.0, tau / jnp.maximum(norms, 1e-12))
        return v + jnp.einsum("k,kd->d", w, diff * scale[:, None])

    v = jax.lax.fori_loop(0, iters, body, v)
    return v, {}


def weak_dp(updates: Arr, weights: Arr, rng: jax.Array,
            stddev: float = 0.002) -> Tuple[Arr, Dict]:
    """Weak differential privacy defense (reference
    ``defense/weak_dp_defense.py``): plain weighted mean + gaussian noise."""
    agg = weighted_mean(updates, weights)
    return agg + stddev * jax.random.normal(rng, agg.shape), {}


def crfl_clip_and_perturb(global_vec: Arr, rng: jax.Array,
                          clip_norm: float = 15.0, stddev: float = 0.002
                          ) -> Arr:
    """CRFL (reference ``defense/crfl_defense.py``) post-aggregation step:
    clip the global model norm then add smoothing noise."""
    norm = jnp.linalg.norm(global_vec)
    clipped = global_vec * jnp.minimum(1.0, clip_norm / jnp.maximum(norm, 1e-12))
    return clipped + stddev * jax.random.normal(rng, global_vec.shape)


# ---------------------------------------------------------------------------
# similarity / statistics based reweighting
# ---------------------------------------------------------------------------

def foolsgold_weights(history: Arr, eps: float = 1e-5) -> Arr:
    """FoolsGold (Fung et al.; reference ``defense/foolsgold_defense.py``):
    down-weight clients whose *historical* aggregate updates are mutually
    similar (sybils collude). ``history`` is [K, D] accumulated updates;
    returns per-client learning weights in [0, 1]."""
    normed = history / jnp.maximum(
        jnp.linalg.norm(history, axis=1, keepdims=True), eps)
    cs = normed @ normed.T - jnp.eye(history.shape[0])
    maxcs = jnp.max(cs, axis=1)
    # pardoning: rescale similarity of honest clients
    pard = jnp.where(maxcs[None, :] > maxcs[:, None],
                     cs * maxcs[:, None] / jnp.maximum(maxcs[None, :], eps), cs)
    wv = 1.0 - jnp.max(pard, axis=1)
    wv = jnp.clip(wv, 0.0, 1.0)
    # logit rescale emphasises separation
    wv = wv / jnp.maximum(jnp.max(wv), eps)
    wv = jnp.clip(wv, eps, 1.0 - eps)
    logit = jnp.log(wv / (1.0 - wv)) + 0.5
    return jnp.clip(logit, 0.0, 1.0)


def foolsgold(updates: Arr, weights: Arr, history: Arr) -> Tuple[Arr, Dict]:
    wv = foolsgold_weights(history)
    return weighted_mean(updates, weights * wv), {"fg_weights": wv}


def three_sigma(updates: Arr, weights: Arr, sigma_factor: float = 3.0
                ) -> Tuple[Arr, Dict]:
    """3-sigma outlier rejection (reference ``defense/three_sigma_defense.py``
    family): score = distance to the coordinate median vector; drop clients
    more than ``sigma_factor`` robust-sigma above the median score (median +
    MAD statistics, so the byzantine scores cannot inflate the threshold)."""
    med = jnp.median(updates, axis=0)
    scores = jnp.linalg.norm(updates - med[None], axis=1)
    mu = jnp.median(scores)
    sd = 1.4826 * jnp.median(jnp.abs(scores - mu)) + 1e-12
    keep = (scores <= mu + sigma_factor * sd).astype(updates.dtype)
    w = weights * keep
    return weighted_mean(updates, w), {"scores": scores, "kept": keep}


def outlier_detection(updates: Arr, weights: Arr, z_threshold: float = 2.5
                      ) -> Tuple[Arr, Dict]:
    """Norm-based robust z-score filter (reference
    ``defense/outlier_detection.py``); median/MAD statistics so outliers
    cannot inflate their own acceptance threshold."""
    norms = jnp.linalg.norm(updates, axis=1)
    mu = jnp.median(norms)
    sd = 1.4826 * jnp.median(jnp.abs(norms - mu)) + 1e-12
    keep = (jnp.abs(norms - mu) <= z_threshold * sd).astype(updates.dtype)
    return weighted_mean(updates, weights * keep), {"kept": keep}


def residual_reweight(updates: Arr, weights: Arr, lam: float = 2.0
                      ) -> Tuple[Arr, Dict]:
    """Residual-based reweighting (Fu et al.; reference
    ``defense/residual_based_reweighting_defense.py``, simplified to its
    IRLS core): weight each client by a Huber-style factor of its residual
    to the coordinate-median model."""
    med = jnp.median(updates, axis=0)
    resid = jnp.linalg.norm(updates - med[None], axis=1)
    mad = jnp.median(jnp.abs(resid - jnp.median(resid))) + 1e-12
    conf = jnp.clip(lam * mad / jnp.maximum(resid, 1e-12), 0.0, 1.0)
    return weighted_mean(updates, weights * conf), {"confidence": conf}


def slsgd(updates: Arr, weights: Arr, trim_b: int = 1, alpha: float = 1.0,
          prev_global: Arr = None) -> Tuple[Arr, Dict]:
    """SLSGD (Xie et al.; reference ``defense/slsgd_defense.py``):
    trimmed-mean aggregation mixed with the previous global model:
    ``(1-alpha) * prev + alpha * trmean``."""
    k = updates.shape[0]
    b = min(trim_b, (k - 1) // 2)
    s = jnp.sort(updates, axis=0)
    kept = s[b:k - b] if b > 0 else s
    agg = jnp.mean(kept, axis=0)
    if prev_global is not None:
        agg = (1.0 - alpha) * prev_global + alpha * agg
    return agg, {}


def robust_learning_rate(updates: Arr, weights: Arr, threshold: int = 2
                         ) -> Tuple[Arr, Dict]:
    """RLR (Ozdayi et al.; reference ``defense/robust_learning_rate_defense.py``):
    per-coordinate sign vote — coordinates where fewer than ``threshold``
    clients agree in sign get their learning rate flipped."""
    sign_sum = jnp.abs(jnp.sum(jnp.sign(updates), axis=0))
    lr_sign = jnp.where(sign_sum >= threshold, 1.0, -1.0)
    return weighted_mean(updates, weights) * lr_sign, {"lr_sign": lr_sign}


def soteria(updates: Arr, weights: Arr, frac: float = 0.5
            ) -> Tuple[Arr, Dict]:
    """Soteria-style representation pruning (reference
    ``soteria_defense.py``): before aggregation, zero the smallest-magnitude
    ``frac`` of each client's update coordinates — the perturbed
    representation defends against gradient-inversion reconstruction while
    keeping the dominant directions."""
    k, d = updates.shape
    cut = jnp.quantile(jnp.abs(updates), frac, axis=1, keepdims=True)
    pruned = jnp.where(jnp.abs(updates) >= cut, updates, 0.0)
    return weighted_mean(pruned, weights), {"pruned_frac": frac}


def wbc(updates: Arr, weights: Arr, iters: int = 8) -> Tuple[Arr, Dict]:
    """White-Blood-Cell clustering defense (reference ``wbc_defense.py``
    shape): 2-means over the update vectors; only the LARGER cluster (the
    presumed-honest majority) is aggregated."""
    k = updates.shape[0]
    # seed centroids at the two most-distant rows (deterministic)
    dists = pairwise_sq_dists(updates)
    flat_idx = jnp.argmax(dists)
    i0, i1 = flat_idx // k, flat_idx % k
    c = jnp.stack([updates[i0], updates[i1]])

    def body(_, c):
        assign = jnp.argmin(
            jnp.stack([jnp.sum((updates - c[0]) ** 2, axis=1),
                       jnp.sum((updates - c[1]) ** 2, axis=1)]), axis=0)
        one = (assign == 1).astype(updates.dtype)[:, None]
        n1 = jnp.maximum(jnp.sum(one), 1.0)
        n0 = jnp.maximum(jnp.sum(1.0 - one), 1.0)
        return jnp.stack([jnp.sum(updates * (1 - one), axis=0) / n0,
                          jnp.sum(updates * one, axis=0) / n1])

    c = jax.lax.fori_loop(0, iters, body, c)
    assign = jnp.argmin(
        jnp.stack([jnp.sum((updates - c[0]) ** 2, axis=1),
                   jnp.sum((updates - c[1]) ** 2, axis=1)]), axis=0)
    # label of the LARGER cluster: cluster 1 wins iff it holds > k/2 rows
    majority = (jnp.sum(assign) > k / 2).astype(jnp.int32)
    keep = (assign == majority).astype(updates.dtype)
    return (weighted_mean(updates, weights * keep),
            {"kept": jnp.sum(keep)})


def cross_round_filter(updates: Arr, weights: Arr, prev: Arr,
                       has_prev: Arr, sim_threshold: float = -0.5
                       ) -> Tuple[Arr, Dict]:
    """Cross-round consistency defense (reference
    ``cross_round_defense.py`` shape): a client whose update direction
    REVERSES versus its own previous round (cosine < threshold) is
    suspicious (oscillating / adaptive poisoning) and dropped this round.
    Clients without history pass through."""
    dot = jnp.sum(updates * prev, axis=1)
    norm = (jnp.linalg.norm(updates, axis=1)
            * jnp.linalg.norm(prev, axis=1) + 1e-12)
    cos = dot / norm
    keep = jnp.where(has_prev > 0,
                     (cos >= sim_threshold).astype(updates.dtype), 1.0)
    return (weighted_mean(updates, weights * keep),
            {"kept": jnp.sum(keep), "mean_cos": jnp.mean(cos)})
