"""Sharded robust aggregation — defenses that never materialize the full
update matrix on one device.

The engine's robust mode emits the round's raw client updates as a
[K, D] matrix. For CNN-sized models a single device holds it easily, but
for the LLM path D is billions — so the defense itself must run SPMD. The
trick: every defense in :mod:`.robust_agg` factors into

  1. per-coordinate statistics (median/trimmed-mean/sign votes) — trivially
     parallel over a feature-sharded matrix,
  2. a [K, K] pairwise-distance Gram (krum/bulyan/wbc/3σ) or per-row norms
     (norm-clip/outlier/RFA) — computed as a ``psum`` of per-shard partial
     sums (K² and K are tiny; D is what's sharded), followed by [K]-sized
     selection weights applied locally, or
  3. an iteration whose [D]-sized iterate stays feature-sharded and only
     exchanges [K] distance fragments per step (RFA's Weiszfeld loop,
     cclip's clipped mean, wbc's 2-means).

Cross-round defense state (FoolsGold's similarity history, cclip momentum,
SLSGD's previous global, cross-round's per-client previous updates) is a
DEVICE-RESIDENT, feature-sharded pytree (:func:`defense_state_init` /
:func:`defense_state_spec`) so stateful defenses fuse too: the engine
threads it through the fused multi-round ``lax.scan`` like ``client_states``
and checkpoints it for crash-resume.

``defend_matrix_sharded`` jits one ``shard_map`` over the mesh's device
axis with the matrix feature-sharded [K, D/n]; only [K, K]/[K] statistics
are replicated. Parity with the host path is asserted in tests.

Coverage: every defense in ``DEFENSE_TYPES`` has a sharded kernel. Two
caveats, both documented where they bite: ``weak_dp``/``crfl`` fold the
shard index into their noise key (like stochastic attacks, the stream
depends on the mesh layout — valid DP noise, but not bit-identical to the
single-host kernel), and ``soteria`` must see one full row at a time for
its per-client quantile (a scanned [D]-sized ``all_gather`` per row — peak
memory stays O(D), never O(K·D)).
"""

from __future__ import annotations

from dataclasses import dataclass
from functools import lru_cache
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ...jax_compat import shard_map
from . import robust_agg

PyTree = Any

# canonical kernel name per accepted alias (mirrors FedMLDefender._dispatch)
_ALIASES = {
    "median": "coordinate_median",
    "geometric_median": "rfa",
    "robust_learning_rate": "rlr",
}

# every built-in defense now has a feature-sharded kernel. Grouped by how
# they factor over the shard (see module docstring); three_sigma keeps the
# distance-to-coordinate-median + median/MAD scores of the host kernel (a
# weaker mean/std variant would let byzantine rows widen the band).
_SHARDED = (
    # selection / per-coordinate statistics (exact)
    "krum", "multi_krum", "bulyan", "coordinate_median", "median",
    "trimmed_mean", "mean", "three_sigma", "rfa", "geometric_median",
    "norm_clip", "outlier_detection", "residual_reweight",
    "robust_learning_rate", "rlr", "wbc", "soteria",
    # stateful (device-resident cross-round state, see defense_state_init)
    "foolsgold", "cclip", "slsgd", "cross_round",
    # stochastic (per-shard noise streams, mesh-layout-dependent)
    "weak_dp", "crfl",
)

# defenses that carry cross-round device state
_STATEFUL = ("foolsgold", "cclip", "slsgd", "cross_round")


def _canon(defense_type: str) -> str:
    return _ALIASES.get(defense_type, defense_type)


def supports_sharded(defense_type: str) -> bool:
    return defense_type in _SHARDED


def sharded_defense_names() -> str:
    """Stable, human-readable list of the sharded-capable defenses (the
    one the error/log messages print)."""
    return ", ".join(sorted(set(_SHARDED)))


def is_stateful(defense_type: str) -> bool:
    return _canon(defense_type) in _STATEFUL


@dataclass(frozen=True)
class DefenseHP:
    """Hashable hyper-parameter bundle for the sharded kernels (frozen so
    the jitted-builder ``lru_cache`` can key on it). Defaults equal the
    host kernels' defaults in :mod:`.robust_agg` — drift here would
    silently break host/sharded parity."""

    byzantine_count: int = 0
    multi_k: int = 1
    trim_fraction: float = 0.1
    norm_bound: float = 5.0
    tau: float = 10.0
    stddev: float = 0.002
    alpha: float = 1.0
    rfa_iters: int = 8
    rfa_tol: float = 0.0
    cclip_iters: int = 3
    wbc_iters: int = 8
    soteria_frac: float = 0.5
    cr_threshold: float = -0.5
    z_threshold: float = 2.5
    resid_lam: float = 2.0
    rlr_threshold: int = 2

    @classmethod
    def from_defender(cls, dfd) -> "DefenseHP":
        from ....utils.confval import get_float
        return cls(
            byzantine_count=int(dfd.byzantine_count),
            multi_k=int(dfd.krum_param_m),
            trim_fraction=float(dfd.trim_fraction),
            norm_bound=float(dfd.norm_bound),
            tau=float(dfd.cclip_tau),
            stddev=float(dfd.dp_stddev),
            alpha=float(dfd.alpha),
            rfa_iters=int(getattr(dfd, "rfa_iters", 8)),
            rfa_tol=float(getattr(dfd, "rfa_tol", 0.0)),
            soteria_frac=get_float(dfd.args, "soteria_frac", 0.5),
            cr_threshold=get_float(dfd.args, "cross_round_threshold", -0.5),
        )


# ---------------------------------------------------------------------------
# cross-round defense state
# ---------------------------------------------------------------------------

def defense_state_init(defense_type: str, n_total: int,
                       d_pad: int) -> Dict[str, jnp.ndarray]:
    """Zero-initialized cross-round state for a stateful defense, GLOBAL
    (unsharded) shapes — the caller places leaves per
    :func:`defense_state_spec`. ``d_pad`` is the feature dim padded to a
    multiple of the device count; ``n_total`` the total client population
    (per-client-keyed state indexes by true client id). Empty dict for
    stateless defenses. Zeros reproduce the host kernels' cold start:
    FoolsGold/cross_round accumulate from nothing, cclip's momentum starts
    at the origin, SLSGD's ``has`` flag skips the prev-global mix."""
    d = _canon(defense_type)
    if d == "foolsgold":
        return {"history": jnp.zeros((n_total, d_pad), jnp.float32)}
    if d == "cclip":
        return {"momentum": jnp.zeros((d_pad,), jnp.float32)}
    if d == "slsgd":
        return {"prev": jnp.zeros((d_pad,), jnp.float32),
                "has": jnp.zeros((), jnp.float32)}
    if d == "cross_round":
        return {"prev": jnp.zeros((n_total, d_pad), jnp.float32),
                "has": jnp.zeros((n_total,), jnp.float32)}
    return {}


def defense_state_spec(defense_type: str, axis: str) -> Dict[str, P]:
    """PartitionSpec per state leaf: [*, D]-shaped leaves are
    feature-sharded over ``axis`` (the history matrices are the BIG state —
    N·D for FoolsGold — and must never gather), [K]/[N]/scalar leaves are
    replicated."""
    d = _canon(defense_type)
    if d == "foolsgold":
        return {"history": P(None, axis)}
    if d == "cclip":
        return {"momentum": P(axis)}
    if d == "slsgd":
        return {"prev": P(axis), "has": P()}
    if d == "cross_round":
        return {"prev": P(None, axis), "has": P()}
    return {}


# ---------------------------------------------------------------------------
# attack injection (on-device, per shard)
# ---------------------------------------------------------------------------

def _apply_attack_shard(attack_type: str, mat_s, byz_mask, key, scale,
                        axis: str):
    """Model-poisoning injection on a FEATURE shard of the update matrix —
    the on-device counterpart of FedMLAttacker.poison_updates. Row-wise
    transforms (flip/zero/replacement) are shard-exact; stochastic attacks
    fold the shard index into the key so noise differs per shard (the
    stream therefore depends on the mesh layout, unlike the host path —
    fine for attacks, which model an adversary, not a reproducible rng)."""
    from ..attack import (byzantine_flip, byzantine_random, byzantine_zero,
                          gaussian_noise, lazy_worker, model_replacement)
    key = jax.random.fold_in(key, jax.lax.axis_index(axis))
    if attack_type == "byzantine_random":
        return byzantine_random(mat_s, byz_mask, key, scale)
    if attack_type == "byzantine_zero":
        return byzantine_zero(mat_s, byz_mask)
    if attack_type == "byzantine_flip":
        return byzantine_flip(mat_s, byz_mask, scale)
    if attack_type == "model_replacement":
        boost = scale if scale != 1.0 else float(mat_s.shape[0])
        return model_replacement(mat_s, byz_mask, boost)
    if attack_type == "gaussian_noise":
        return gaussian_noise(mat_s, key, scale)
    if attack_type == "lazy_worker":
        return lazy_worker(mat_s, byz_mask, key)
    return mat_s


# ---------------------------------------------------------------------------
# per-shard kernel helpers (pure SPMD bodies, run INSIDE a shard_map)
# ---------------------------------------------------------------------------

# Partial-pour row masking (buffered-async defended pours): the pour
# program's [K] buffer shape is compiled once, so a partial pour (fewer
# than K arrivals — drained event heap, pour-timeout valve) pads with
# zero rows and hands the kernels a [K] validity mask. Masking semantics
# per kernel family, all reducing to the unmasked code at mask=None
# (the sync paths never pass a mask — their behavior is bit-identical):
#
# * weight-folded kernels (mean, norm_clip, rfa, cclip, soteria, rlr)
#   are mask-exact already: padded rows carry weight 0 (and sign(0) = 0
#   for rlr's votes), so they vanish from every weighted reduction.
# * coordinate sorts (median, trimmed_mean, slsgd) sort padded rows to
#   +inf and index the valid prefix dynamically (_masked_median /
#   _masked_sorted_window_mean).
# * robust statistics (three_sigma, outlier_detection,
#   residual_reweight) take their median/MAD over valid rows only.
# * Gram selections (krum, multi_krum, bulyan, wbc) add +1e30 to any
#   pair involving a padded row: every valid row's score gains the SAME
#   inflated tail, so the relative order among valid rows is preserved
#   and padded rows are never selected (while a selection size larger
#   than the valid count degrades toward the zero rows — a conservative,
#   smaller step — documented rather than hidden).
# * stateful scatters (foolsgold, cross_round) must not write padded
#   rows into per-client history; callers pad ``ids`` with ids DISJOINT
#   from the valid rows so the masked writes are exact no-ops.

def _masked_median(x: jnp.ndarray, mask: jnp.ndarray) -> jnp.ndarray:
    """Median over rows with ``mask > 0`` (axis 0; works for [K] vectors
    and [K, D] matrices). Invalid rows sort to +inf; the two middle
    elements of the valid prefix are indexed dynamically."""
    key = mask if x.ndim == 1 else mask[:, None]
    big = jnp.where(key > 0, x, jnp.inf)
    s = jnp.sort(big, axis=0)
    n = jnp.maximum(jnp.sum(mask).astype(jnp.int32), 1)
    return 0.5 * (s[(n - 1) // 2] + s[n // 2])


def _masked_sorted_window_mean(mat_s: jnp.ndarray, mask: jnp.ndarray,
                               b) -> jnp.ndarray:
    """Per-coordinate mean of the sorted valid rows with ``b`` trimmed
    from each side (the masked trimmed-mean / SLSGD core). ``b`` may be
    traced; it is clamped to the valid count."""
    k = mat_s.shape[0]
    big = jnp.where(mask[:, None] > 0, mat_s, jnp.inf)
    s = jnp.sort(big, axis=0)
    n = jnp.maximum(jnp.sum(mask).astype(jnp.int32), 1)
    b = jnp.clip(jnp.asarray(b, jnp.int32), 0, (n - 1) // 2)
    idx = jnp.arange(k)[:, None]
    keep = ((idx >= b) & (idx < n - b)).astype(mat_s.dtype)
    s = jnp.where(jnp.isfinite(s), s, 0.0)
    return (jnp.sum(s * keep, axis=0)
            / jnp.maximum(jnp.sum(keep, axis=0), 1.0))


def _mask_dists(dists: jnp.ndarray,
                mask: Optional[jnp.ndarray]) -> jnp.ndarray:
    """+1e30 on every pair involving an invalid row: valid rows' score
    tails inflate identically (order preserved), invalid rows score off
    the chart and are never selected."""
    if mask is None:
        return dists
    valid = mask[:, None] * mask[None, :]
    return dists + (1.0 - valid) * 1e30


def _psum_dists(mat_s: jnp.ndarray, axis: str) -> jnp.ndarray:
    """Replicated [K, K] squared-distance Gram from per-shard partials."""
    return jax.lax.psum(robust_agg.pairwise_sq_dists(mat_s), axis)


def _psum_row_norms(mat_s: jnp.ndarray, axis: str) -> jnp.ndarray:
    """Replicated [K] euclidean row norms from per-shard squared sums."""
    return jnp.sqrt(jax.lax.psum(jnp.sum(mat_s * mat_s, axis=1), axis))


def _selection_weights(defense_type: str, dists: jnp.ndarray,
                       weights: jnp.ndarray, byzantine_count: int,
                       multi_k: int) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """[K] aggregation weights from the replicated [K, K] distance matrix,
    plus the [K] selection mask (the defense's per-client verdict)."""
    k = dists.shape[0]
    if defense_type in ("krum", "multi_krum"):
        m = 1 if defense_type == "krum" else multi_k
        closest = max(k - byzantine_count - 2, 1)
        sorted_d = jnp.sort(dists, axis=1)
        scores = jnp.sum(sorted_d[:, 1:closest + 1], axis=1)
        order = jnp.argsort(scores)
        sel = jnp.zeros(k).at[order[:m]].set(1.0)
        return sel * weights, sel
    return weights, jnp.ones(k, weights.dtype)  # mean


def _bulyan_shard(mat_s, weights, axis, hp: DefenseHP, mask=None):
    """Bulyan (El Mhamdi et al.) on a feature shard: iterated Multi-Krum
    selection from the psum'd [K, K] Gram (theta = K - 2f rows), then the
    per-coordinate nearest-to-median trimmed mean — purely local once the
    replicated selection is known. Mirrors robust_agg.bulyan row for row.
    Under a partial-pour ``mask``, padded rows are never preferred; a
    theta larger than the valid count pulls the trimmed mean toward the
    zero padding (a conservative, smaller step — see the mask notes)."""
    k = mat_s.shape[0]
    f = hp.byzantine_count
    theta = max(k - 2 * f, 1)
    scores = robust_agg.krum_scores_from_dists(
        _mask_dists(_psum_dists(mat_s, axis), mask), f)
    _, sel = jax.lax.top_k(-scores, theta)
    chosen = mat_s[sel]
    beta = max(theta - 2 * f, 1)
    med = jnp.median(chosen, axis=0)
    dist_to_med = jnp.abs(chosen - med[None])
    _, nearest = jax.lax.top_k(-dist_to_med.T, beta)  # [D/n, beta]
    vals = jnp.take_along_axis(chosen.T, nearest, axis=1)
    return jnp.mean(vals, axis=1), jnp.zeros(k).at[sel].set(1.0)


def _rfa_shard(mat_s, weights, axis, hp: DefenseHP, eps: float = 1e-8):
    """RFA / geometric median (Pillutla et al.): smoothed Weiszfeld as a
    ``lax.while_loop`` whose [D]-sized estimate stays feature-sharded —
    each iteration exchanges only the [K] squared-distance fragments
    (psum of per-shard partial sums); the estimate never gathers.
    Mask-exact under partial pours: padded rows carry weight 0.

    ``rfa_tol > 0`` adds a convergence early exit: stop once the
    iterate's global movement (psum'd across shards, so every shard
    agrees on the verdict) drops below the tolerance. Parity story vs
    the host kernel (:func:`robust_agg.geometric_median`): at the
    default ``rfa_tol: 0`` both run the exact fixed trip count and are
    bit-parity-tested; with a tolerance both kernels share the SAME
    movement rule, but the sharded psum associates float sums
    differently than the host's flat reduction, so near the exit
    boundary the two may differ by one iteration — parity then holds to
    the tolerance, not to the bit (documented, regression-tested)."""
    w = weights / jnp.maximum(jnp.sum(weights), 1e-12)
    v0 = jnp.einsum("k,kd->d", w, mat_s)

    def iterate(v):
        part = jnp.sum((mat_s - v[None]) ** 2, axis=1)
        dist = jnp.sqrt(jax.lax.psum(part, axis) + eps)
        beta = w / jnp.maximum(dist, eps)
        beta = beta / jnp.maximum(jnp.sum(beta), 1e-12)
        return jnp.einsum("k,kd->d", beta, mat_s)

    if hp.rfa_tol <= 0.0:  # fixed trip count: the bit-parity default
        def step(carry):
            i, v = carry
            return i + 1, iterate(v)

        _, v = jax.lax.while_loop(lambda c: c[0] < hp.rfa_iters, step,
                                  (jnp.int32(0), v0))
        return v

    def step_tol(carry):
        i, v, _ = carry
        new = iterate(v)
        moved = jnp.sqrt(jax.lax.psum(jnp.sum((new - v) ** 2), axis))
        return i + 1, new, moved

    def cond_tol(carry):
        i, _, moved = carry
        return (i < hp.rfa_iters) & (moved > hp.rfa_tol)

    _, v, _ = jax.lax.while_loop(
        cond_tol, step_tol, (jnp.int32(0), v0, jnp.float32(jnp.inf)))
    return v


def _three_sigma_shard(mat_s, weights, axis, mask=None):
    """host parity: score_i = ||u_i - coord_median||; keep within
    median(score) + 3 * 1.4826 * MAD(score). Masked: the median/MAD
    statistics run over valid rows only (zero padding would drag the
    coordinate median and shrink the band)."""
    if mask is None:
        med = jnp.median(mat_s, axis=0)
    else:
        med = _masked_median(mat_s, mask)
    part = jnp.sum((mat_s - med[None]) ** 2, axis=1)
    scores = jnp.sqrt(jax.lax.psum(part, axis))
    if mask is None:
        mu = jnp.median(scores)
        sd = 1.4826 * jnp.median(jnp.abs(scores - mu)) + 1e-12
        keep = (scores <= mu + 3.0 * sd).astype(weights.dtype)
    else:
        mu = _masked_median(scores, mask)
        sd = 1.4826 * _masked_median(jnp.abs(scores - mu), mask) + 1e-12
        keep = ((scores <= mu + 3.0 * sd)
                & (mask > 0)).astype(weights.dtype)
    return robust_agg.weighted_mean(mat_s, weights * keep), keep


def _norm_clip_shard(mat_s, weights, axis, hp: DefenseHP):
    norms = _psum_row_norms(mat_s, axis)
    scale = jnp.minimum(1.0, hp.norm_bound / jnp.maximum(norms, 1e-12))
    return robust_agg.weighted_mean(mat_s * scale[:, None], weights)


def _outlier_shard(mat_s, weights, axis, hp: DefenseHP, mask=None):
    norms = _psum_row_norms(mat_s, axis)
    if mask is None:
        mu = jnp.median(norms)
        sd = 1.4826 * jnp.median(jnp.abs(norms - mu)) + 1e-12
        keep = (jnp.abs(norms - mu)
                <= hp.z_threshold * sd).astype(mat_s.dtype)
    else:
        mu = _masked_median(norms, mask)
        sd = 1.4826 * _masked_median(jnp.abs(norms - mu), mask) + 1e-12
        keep = ((jnp.abs(norms - mu) <= hp.z_threshold * sd)
                & (mask > 0)).astype(mat_s.dtype)
    return robust_agg.weighted_mean(mat_s, weights * keep), keep


def _residual_shard(mat_s, weights, axis, hp: DefenseHP, mask=None):
    if mask is None:
        med = jnp.median(mat_s, axis=0)
    else:
        med = _masked_median(mat_s, mask)
    part = jnp.sum((mat_s - med[None]) ** 2, axis=1)
    resid = jnp.sqrt(jax.lax.psum(part, axis))
    if mask is None:
        mad = jnp.median(jnp.abs(resid - jnp.median(resid))) + 1e-12
    else:
        mad = _masked_median(jnp.abs(resid - _masked_median(resid, mask)),
                             mask) + 1e-12
    conf = jnp.clip(hp.resid_lam * mad / jnp.maximum(resid, 1e-12), 0.0, 1.0)
    if mask is not None:
        conf = conf * mask
    return robust_agg.weighted_mean(mat_s, weights * conf), conf


def _rlr_shard(mat_s, weights, axis, hp: DefenseHP):
    """Sign votes and the learning-rate flip are per-coordinate — fully
    local on the shard; nothing to reduce."""
    sign_sum = jnp.abs(jnp.sum(jnp.sign(mat_s), axis=0))
    lr_sign = jnp.where(sign_sum >= hp.rlr_threshold, 1.0, -1.0)
    return robust_agg.weighted_mean(mat_s, weights) * lr_sign


def _wbc_shard(mat_s, weights, axis, hp: DefenseHP, mask=None):
    """2-means over the rows with feature-sharded [2, D/n] centroids;
    assignments come from psum'd squared distances each iteration, the
    centroid update is a local per-coordinate mean. Masked: padded rows
    join neither the centroid seeding (their pairs score -1) nor the
    centroid means nor the majority vote."""
    k = mat_s.shape[0]
    valid = jnp.ones(k, mat_s.dtype) if mask is None else mask
    dists = _psum_dists(mat_s, axis)
    if mask is not None:
        dists = jnp.where(valid[:, None] * valid[None, :] > 0, dists, -1.0)
    flat_idx = jnp.argmax(dists)
    c = jnp.stack([mat_s[flat_idx // k], mat_s[flat_idx % k]])

    def assign_to(c):
        d0 = jax.lax.psum(jnp.sum((mat_s - c[0]) ** 2, axis=1), axis)
        d1 = jax.lax.psum(jnp.sum((mat_s - c[1]) ** 2, axis=1), axis)
        return jnp.argmin(jnp.stack([d0, d1]), axis=0)

    def body(_, c):
        one = ((assign_to(c) == 1).astype(mat_s.dtype) * valid)[:, None]
        zero = ((valid - one[:, 0]))[:, None]
        n1 = jnp.maximum(jnp.sum(one), 1.0)
        n0 = jnp.maximum(jnp.sum(zero), 1.0)
        return jnp.stack([jnp.sum(mat_s * zero, axis=0) / n0,
                          jnp.sum(mat_s * one, axis=0) / n1])

    c = jax.lax.fori_loop(0, hp.wbc_iters, body, c)
    assign = assign_to(c)
    majority = (jnp.sum(assign * valid)
                > jnp.sum(valid) / 2).astype(jnp.int32)
    keep = (assign == majority).astype(mat_s.dtype) * valid
    return robust_agg.weighted_mean(mat_s, weights * keep), keep


def _soteria_shard(mat_s, weights, axis, hp: DefenseHP, true_d: int):
    """Per-row magnitude quantile needs the WHOLE row: scan the K rows,
    all_gather one [D] row at a time (peak memory O(D), never O(K·D)),
    take the quantile over the TRUE feature dim (padding zeros would skew
    it), then prune locally on the shard."""
    def cut_for(i):
        row = jax.lax.all_gather(mat_s[i], axis, tiled=True)[:true_d]
        return jnp.quantile(jnp.abs(row), hp.soteria_frac)

    cuts = jax.lax.map(cut_for, jnp.arange(mat_s.shape[0]))
    pruned = jnp.where(jnp.abs(mat_s) >= cuts[:, None], mat_s, 0.0)
    return robust_agg.weighted_mean(pruned, weights)


def _weak_dp_shard(mat_s, weights, axis, hp: DefenseHP, key):
    """Weighted mean + gaussian noise generated per shard (shard index
    folded into the key, like stochastic attacks): valid DP noise of the
    configured stddev, but the stream depends on the mesh layout — not
    bit-identical to the single-host kernel."""
    agg = robust_agg.weighted_mean(mat_s, weights)
    key = jax.random.fold_in(key, jax.lax.axis_index(axis))
    return agg + hp.stddev * jax.random.normal(key, agg.shape)


def _crfl_shard(mat_s, weights, axis, hp: DefenseHP, key):
    """CRFL post-aggregation clip (global norm via psum) + per-shard
    smoothing noise (same mesh-layout caveat as weak_dp)."""
    agg = robust_agg.weighted_mean(mat_s, weights)
    norm = jnp.sqrt(jax.lax.psum(jnp.sum(agg * agg), axis))
    clipped = agg * jnp.minimum(1.0, hp.norm_bound
                                / jnp.maximum(norm, 1e-12))
    key = jax.random.fold_in(key, jax.lax.axis_index(axis))
    return clipped + hp.stddev * jax.random.normal(key, clipped.shape)


def _foolsgold_weights_shard(hist_rows, axis, eps: float = 1e-5):
    """robust_agg.foolsgold_weights on feature-sharded history rows: row
    norms and the [K, K] cosine Gram come from psum'd per-shard partials;
    the pardoning/logit rescale is [K]-sized and replicated. Any drift
    from the host kernel would silently break sharded/host parity."""
    k = hist_rows.shape[0]
    sq = jax.lax.psum(jnp.sum(hist_rows * hist_rows, axis=1), axis)
    normed = hist_rows / jnp.maximum(jnp.sqrt(sq), eps)[:, None]
    cs = jax.lax.psum(normed @ normed.T, axis) - jnp.eye(k)
    maxcs = jnp.max(cs, axis=1)
    pard = jnp.where(maxcs[None, :] > maxcs[:, None],
                     cs * maxcs[:, None] / jnp.maximum(maxcs[None, :], eps),
                     cs)
    wv = jnp.clip(1.0 - jnp.max(pard, axis=1), 0.0, 1.0)
    wv = wv / jnp.maximum(jnp.max(wv), eps)
    wv = jnp.clip(wv, eps, 1.0 - eps)
    logit = jnp.log(wv / (1.0 - wv)) + 0.5
    return jnp.clip(logit, 0.0, 1.0)


def _cclip_shard(mat_s, weights, axis, hp: DefenseHP, state):
    """Centered clipping with the momentum vector as feature-sharded
    cross-round state; per-iteration diff norms psum across shards."""
    v = state["momentum"]
    w = weights / jnp.maximum(jnp.sum(weights), 1e-12)

    def body(_, v):
        diff = mat_s - v[None]
        norms = jnp.sqrt(jax.lax.psum(jnp.sum(diff * diff, axis=1), axis))
        scale = jnp.minimum(1.0, hp.tau / jnp.maximum(norms, 1e-12))
        return v + jnp.einsum("k,kd->d", w, diff * scale[:, None])

    v = jax.lax.fori_loop(0, hp.cclip_iters, body, v)
    return v, {"momentum": v}


def _slsgd_shard(mat_s, weights, axis, hp: DefenseHP, state, mask=None):
    """SLSGD trimmed mean (per-coordinate, local) mixed with the previous
    global — a feature-sharded state leaf; round 0 (has == 0) skips the
    mix exactly like the host kernel's ``prev_global is None``. Masked:
    the trim window covers the sorted VALID rows only."""
    k = mat_s.shape[0]
    if mask is None:
        b = min(max(hp.byzantine_count, 1), (k - 1) // 2)
        s = jnp.sort(mat_s, axis=0)
        agg = jnp.mean(s[b:k - b] if b > 0 else s, axis=0)
    else:
        agg = _masked_sorted_window_mean(mat_s, mask,
                                         max(hp.byzantine_count, 1))
    mixed = jnp.where(state["has"] > 0,
                      (1.0 - hp.alpha) * state["prev"] + hp.alpha * agg, agg)
    return mixed, {"prev": mixed, "has": jnp.float32(1)}


def _cross_round_shard(mat_s, weights, axis, hp: DefenseHP, state, ids,
                       mask=None):
    """Cross-round consistency: per-client previous updates live in a
    feature-sharded [N, D/n] state matrix keyed by TRUE client id; cosines
    come from psum'd per-shard dot/norm fragments. Clients without history
    pass through, as on the host path. Masked rows neither write their
    (zero) row into the state nor mark history as present — callers pad
    ``ids`` disjoint from the valid rows, so the guarded writes are
    no-ops."""
    prev = state["prev"][ids]
    has = state["has"][ids]
    dot = jax.lax.psum(jnp.sum(mat_s * prev, axis=1), axis)
    n_cur = _psum_row_norms(mat_s, axis)
    n_prev = _psum_row_norms(prev, axis)
    cos = dot / (n_cur * n_prev + 1e-12)
    keep = jnp.where(has > 0,
                     (cos >= hp.cr_threshold).astype(mat_s.dtype), 1.0)
    if mask is None:
        new_state = {"prev": state["prev"].at[ids].set(mat_s),
                     "has": state["has"].at[ids].set(1.0)}
    else:
        keep = keep * mask
        new_state = {
            "prev": state["prev"].at[ids].set(
                jnp.where(mask[:, None] > 0, mat_s, prev)),
            "has": state["has"].at[ids].set(jnp.maximum(mask, has)),
        }
    return robust_agg.weighted_mean(mat_s, weights * keep), new_state, keep


def _foolsgold_shard(mat_s, weights, axis, state, ids, mask=None):
    """FoolsGold with the accumulated history as feature-sharded [N, D/n]
    state: add this round's (post-attack) rows into the clients' history
    FIRST — the host kernel scores similarities on the updated history —
    then down-weight mutually-similar clients. Masked rows add nothing to
    history (ids are padded disjoint, see the mask notes)."""
    add = mat_s if mask is None else mask[:, None] * mat_s
    hist_rows = state["history"][ids] + add
    new_state = {"history": state["history"].at[ids].set(hist_rows)}
    wv = _foolsgold_weights_shard(hist_rows, axis)
    return robust_agg.weighted_mean(mat_s, weights * wv), new_state, wv


# ---------------------------------------------------------------------------
# the unified per-shard kernel
# ---------------------------------------------------------------------------

def defend_shard_stateful(
    mat_s: jnp.ndarray,
    weights: jnp.ndarray,
    axis: str,
    defense_type: str,
    hp: Optional[DefenseHP] = None,
    state: Optional[Dict[str, jnp.ndarray]] = None,
    ids: Optional[jnp.ndarray] = None,
    key: Optional[jax.Array] = None,
    true_d: Optional[int] = None,
    row_mask: Optional[jnp.ndarray] = None,
) -> Tuple[jnp.ndarray, Dict[str, jnp.ndarray], jnp.ndarray]:
    """The per-shard defense kernel: [K, D/n] feature shard + replicated
    [K] weights (+ optional cross-round ``state``, sampled client ``ids``,
    noise ``key``) -> (defended aggregate shard [D/n], new state,
    [K] verdict). Pure SPMD body meant to run INSIDE an existing
    ``shard_map`` over ``axis`` — this is the ONE implementation shared by
    :func:`defend_matrix_sharded` (host-dispatch path) and the engine's
    fused robust round program; any drift between the two would silently
    break their client-for-client parity.

    The **verdict** is the defense's per-client effective inclusion in
    [0, 1] (1 = fully kept, 0 = excluded): the krum/bulyan selection mask,
    three_sigma/outlier/wbc/cross_round keep flags, residual confidences,
    foolsgold weights. Coordinate-wise and norm-shaping defenses (median,
    trimmed_mean, rfa, norm_clip, soteria, weak_dp, crfl, cclip, slsgd)
    have no per-client exclusion notion and report all-ones. It is
    replicated and [K]-sized — free to emit — and feeds the selection
    subsystem's reputation scores with zero extra dispatches.

    ``row_mask`` (optional [K], 1 = real row) marks partial-pour padding
    (buffered-async defended pours); ``None`` — every sync path — runs
    the exact unmasked code, bit-identical to before. See the mask notes
    above the helpers for the per-family semantics."""
    hp = hp or DefenseHP()
    state = state if state is not None else {}
    ones = jnp.ones(mat_s.shape[0], jnp.float32)
    mask = row_mask
    d = _canon(defense_type)
    if d == "mean":
        return robust_agg.weighted_mean(mat_s, weights), state, ones
    if d == "coordinate_median":
        if mask is None:
            return (robust_agg.coordinate_median(mat_s, weights)[0], state,
                    ones)
        return _masked_median(mat_s, mask), state, ones
    if d == "trimmed_mean":
        if mask is None:
            return (robust_agg.trimmed_mean(mat_s, weights,
                                            hp.trim_fraction)[0], state,
                    ones)
        n = jnp.sum(mask).astype(jnp.float32)
        b = jnp.floor(n * jnp.float32(hp.trim_fraction) + 1e-6)
        return _masked_sorted_window_mean(mat_s, mask, b), state, ones
    if d == "three_sigma":
        vec, keep = _three_sigma_shard(mat_s, weights, axis, mask=mask)
        return vec, state, keep
    if d == "bulyan":
        vec, sel = _bulyan_shard(mat_s, weights, axis, hp, mask=mask)
        return vec, state, sel
    if d == "rfa":
        return _rfa_shard(mat_s, weights, axis, hp), state, ones
    if d == "norm_clip":
        return _norm_clip_shard(mat_s, weights, axis, hp), state, ones
    if d == "outlier_detection":
        vec, keep = _outlier_shard(mat_s, weights, axis, hp, mask=mask)
        return vec, state, keep
    if d == "residual_reweight":
        vec, conf = _residual_shard(mat_s, weights, axis, hp, mask=mask)
        return vec, state, conf
    if d == "rlr":
        return _rlr_shard(mat_s, weights, axis, hp), state, ones
    if d == "wbc":
        vec, keep = _wbc_shard(mat_s, weights, axis, hp, mask=mask)
        return vec, state, keep
    if d == "soteria":
        if true_d is None:
            raise ValueError("soteria's per-row quantile needs true_d "
                             "(the unpadded feature dim)")
        return (_soteria_shard(mat_s, weights, axis, hp, int(true_d)),
                state, ones)
    if d == "weak_dp":
        return _weak_dp_shard(mat_s, weights, axis, hp, key), state, ones
    if d == "crfl":
        return _crfl_shard(mat_s, weights, axis, hp, key), state, ones
    if d == "foolsgold":
        vec, new_state, wv = _foolsgold_shard(mat_s, weights, axis, state,
                                              ids, mask=mask)
        return vec, new_state, wv
    if d == "cclip":
        vec, new_state = _cclip_shard(mat_s, weights, axis, hp, state)
        return vec, new_state, ones
    if d == "slsgd":
        vec, new_state = _slsgd_shard(mat_s, weights, axis, hp, state,
                                      mask=mask)
        return vec, new_state, ones
    if d == "cross_round":
        vec, new_state, keep = _cross_round_shard(mat_s, weights, axis, hp,
                                                  state, ids, mask=mask)
        return vec, new_state, keep
    # krum / multi_krum: selection weights from the psum'd (masked) Gram
    dists = _mask_dists(_psum_dists(mat_s, axis), mask)
    sel_w, sel = _selection_weights(d, dists, weights,
                                    hp.byzantine_count, hp.multi_k)
    return robust_agg.weighted_mean(mat_s, sel_w), state, sel


def defend_shard(mat_s: jnp.ndarray, weights: jnp.ndarray, axis: str,
                 defense_type: str, byzantine_count: int = 0,
                 multi_k: int = 1,
                 trim_fraction: float = 0.1) -> jnp.ndarray:
    """Back-compat stateless entry point (PR 2 signature): builds a
    :class:`DefenseHP` and drops the (empty) state. Stateful defenses must
    go through :func:`defend_shard_stateful`."""
    if is_stateful(defense_type):
        raise ValueError(f"{defense_type!r} carries cross-round state; "
                         "call defend_shard_stateful with a state pytree")
    hp = DefenseHP(byzantine_count=byzantine_count, multi_k=multi_k,
                   trim_fraction=trim_fraction)
    vec, _, _ = defend_shard_stateful(mat_s, weights, axis, defense_type,
                                      hp)
    return vec


# ---------------------------------------------------------------------------
# host-dispatch entry point (one shard_map over the mesh)
# ---------------------------------------------------------------------------

@lru_cache(maxsize=64)
def _build_sharded_fn(mesh: Mesh, axis: str, defense_type: str,
                      hp: DefenseHP, has_state: bool, true_d: int,
                      return_matrix: bool,
                      attack_type: Optional[str] = None,
                      attack_scale: float = 1.0,
                      has_mask: bool = False):
    """One compiled kernel per (mesh, defense, params); jit re-traces only
    on new shapes — without this cache every round would recompile. NOTE:
    inputs are NOT donated here — the cached kernel is shared by engines
    and tests, and donating would delete callers' arrays behind their
    backs; the fused engine path (which owns its buffers) donates."""
    state_spec = defense_state_spec(defense_type, axis) if has_state else {}

    def body(mat_s, weights, byz_mask, akey, dkey, state, ids, row_mask):
        # mat_s: [K, D/n] local shard
        if attack_type is not None:
            mat_s = _apply_attack_shard(attack_type, mat_s, byz_mask, akey,
                                        attack_scale, axis)
        vec, new_state, verdict = defend_shard_stateful(
            mat_s, weights, axis, defense_type, hp, state=state, ids=ids,
            key=dkey, true_d=true_d,
            row_mask=row_mask if has_mask else None)
        out = (vec, new_state, verdict)
        return out + (mat_s,) if return_matrix else out

    out_specs = (P(axis), state_spec, P())
    if return_matrix:
        out_specs = out_specs + (P(None, axis),)
    return jax.jit(shard_map(
        body, mesh=mesh,
        in_specs=(P(None, axis), P(), P(), P(), P(), state_spec, P(), P()),
        out_specs=out_specs,
        check_vma=False,
    ))


def defend_matrix_sharded(
    mesh: Mesh,
    axis: str,
    mat: jnp.ndarray,
    weights: jnp.ndarray,
    defense_type: str,
    byzantine_count: int = 0,
    multi_k: int = 1,
    trim_fraction: float = 0.1,
    attack_type: Optional[str] = None,
    attack_scale: float = 1.0,
    byz_mask: Optional[jnp.ndarray] = None,
    attack_key: Optional[jax.Array] = None,
    hp: Optional[DefenseHP] = None,
    state: Optional[Dict[str, jnp.ndarray]] = None,
    ids: Optional[jnp.ndarray] = None,
    defense_key: Optional[jax.Array] = None,
    return_matrix: bool = False,
    return_verdict: bool = False,
    row_mask: Optional[jnp.ndarray] = None,
):
    """[K, D] (feature-sharded over ``axis``) -> defended aggregate [D]
    (feature-sharded). The caller owns placement; this never gathers D
    (except soteria's documented one-row-at-a-time scan). When
    ``attack_type`` is set, model poisoning is injected ON DEVICE on the
    sharded matrix before the defense (the adversarial-evaluation
    pipeline without any host round-trip).

    Returns ``vec`` for stateless defenses; ``(vec, new_state)`` for
    stateful ones (pass the previous round's ``state`` and the sampled
    client ``ids``, or both default to a cold start over ``K`` clients);
    with ``return_matrix=True`` the post-attack sharded matrix is appended
    (the contribution assessor's input — it must see what the defense
    saw); with ``return_verdict=True`` the [K] per-client verdict (see
    :func:`defend_shard_stateful`) is appended LAST — the selection
    subsystem's reputation input; ``row_mask`` marks partial-pour padding
    rows (see :func:`defend_shard_stateful`)."""
    if not supports_sharded(defense_type):
        raise ValueError(
            f"defense_type {defense_type!r} has no sharded kernel; host "
            f"fallback required. Sharded defenses: "
            f"{sharded_defense_names()}")

    if hp is None:
        hp = DefenseHP(byzantine_count=byzantine_count, multi_k=multi_k,
                       trim_fraction=float(trim_fraction))
    n = mesh.shape[axis]
    d = mat.shape[1]
    pad = (-d) % n
    stateful = is_stateful(defense_type)
    fn = _build_sharded_fn(mesh, axis, defense_type, hp, stateful, d,
                           bool(return_matrix),
                           attack_type, float(attack_scale),
                           has_mask=row_mask is not None)
    if pad:
        mat = jnp.pad(mat, ((0, 0), (0, pad)))
    mat = jax.device_put(mat, NamedSharding(mesh, P(None, axis)))
    k = mat.shape[0]
    if byz_mask is None:
        byz_mask = jnp.zeros(k, jnp.float32)
    if attack_key is None:
        attack_key = jax.random.PRNGKey(0)
    if defense_key is None:
        defense_key = jax.random.PRNGKey(0)
    if ids is None:
        ids = jnp.arange(k, dtype=jnp.int32)
    if stateful and state is None:
        # cold start must cover the LARGEST client id, not just K rows —
        # jax clamps out-of-range gather/scatter indices, which would
        # silently merge every too-large id into the last history row
        n_total = max(k, int(jnp.max(jnp.asarray(ids))) + 1)
        state = jax.tree_util.tree_map(
            lambda z, s: jax.device_put(z, NamedSharding(mesh, s)),
            defense_state_init(defense_type, n_total, d + pad),
            defense_state_spec(defense_type, axis))
    if row_mask is None:
        row_mask = jnp.ones(k, jnp.float32)
    out = fn(mat, jnp.asarray(weights, jnp.float32),
             jnp.asarray(byz_mask, jnp.float32), attack_key, defense_key,
             state if stateful else {}, jnp.asarray(ids, jnp.int32),
             jnp.asarray(row_mask, jnp.float32))
    vec, new_state, verdict = out[0], out[1], out[2]
    result = (vec[:d],)
    if stateful:
        result = result + (new_state,)
    if return_matrix:
        result = result + (out[3],)
    if return_verdict:
        result = result + (verdict,)
    return result[0] if len(result) == 1 else result
