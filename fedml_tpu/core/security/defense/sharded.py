"""Sharded robust aggregation — defenses that never materialize the full
update matrix on one device.

The engine's robust mode emits the round's raw client updates as a
[K, D] matrix. For CNN-sized models a single device holds it easily, but
for the LLM path D is billions — so the defense itself must run SPMD. The
trick: every geometry defense in :mod:`.robust_agg` factors into

  1. per-coordinate statistics (median/trimmed-mean) — trivially parallel
     over a feature-sharded matrix, or
  2. a [K, K] pairwise-distance Gram (krum/bulyan/wbc/3σ) — computed as a
     ``psum`` of per-shard partial distances (K² is tiny; D is what's
     sharded), followed by [K]-sized selection weights applied locally.

``defend_matrix_sharded`` jits one ``shard_map`` over the mesh's device
axis with the matrix feature-sharded [K, D/n]; only [K, K]/[K] statistics
are replicated. Parity with the host path is asserted in tests.
"""

from __future__ import annotations

from functools import lru_cache
from typing import Dict, Optional, Tuple

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ...jax_compat import shard_map
from . import robust_agg

# defenses expressible as: selection weights from psum'd statistics, then a
# local weighted reduction over the feature shard. three_sigma uses
# distance-to-coordinate-median + median/MAD scores exactly like the host
# kernel (a weaker mean/std variant would let byzantine rows widen the band)
_SHARDED = ("krum", "multi_krum", "coordinate_median", "median",
            "trimmed_mean", "mean", "three_sigma")


def _apply_attack_shard(attack_type: str, mat_s, byz_mask, key, scale,
                        axis: str):
    """Model-poisoning injection on a FEATURE shard of the update matrix —
    the on-device counterpart of FedMLAttacker.poison_updates. Row-wise
    transforms (flip/zero/replacement) are shard-exact; stochastic attacks
    fold the shard index into the key so noise differs per shard (the
    stream therefore depends on the mesh layout, unlike the host path —
    fine for attacks, which model an adversary, not a reproducible rng)."""
    from ..attack import (byzantine_flip, byzantine_random, byzantine_zero,
                          gaussian_noise, lazy_worker, model_replacement)
    key = jax.random.fold_in(key, jax.lax.axis_index(axis))
    if attack_type == "byzantine_random":
        return byzantine_random(mat_s, byz_mask, key, scale)
    if attack_type == "byzantine_zero":
        return byzantine_zero(mat_s, byz_mask)
    if attack_type == "byzantine_flip":
        return byzantine_flip(mat_s, byz_mask, scale)
    if attack_type == "model_replacement":
        boost = scale if scale != 1.0 else float(mat_s.shape[0])
        return model_replacement(mat_s, byz_mask, boost)
    if attack_type == "gaussian_noise":
        return gaussian_noise(mat_s, key, scale)
    if attack_type == "lazy_worker":
        return lazy_worker(mat_s, byz_mask, key)
    return mat_s


def defend_shard(mat_s: jnp.ndarray, weights: jnp.ndarray, axis: str,
                 defense_type: str, byzantine_count: int = 0,
                 multi_k: int = 1,
                 trim_fraction: float = 0.1) -> jnp.ndarray:
    """The per-shard defense kernel: [K, D/n] feature shard + replicated
    [K] weights -> defended aggregate shard [D/n]. Pure SPMD body meant to
    run INSIDE an existing ``shard_map`` over ``axis`` — this is the ONE
    implementation shared by :func:`defend_matrix_sharded` (host-dispatch
    path) and the engine's fused robust round program; any drift between
    the two would silently break their client-for-client parity."""
    if defense_type in ("coordinate_median", "median"):
        vec, _ = robust_agg.coordinate_median(mat_s, weights)
        return vec
    if defense_type == "trimmed_mean":
        vec, _ = robust_agg.trimmed_mean(mat_s, weights, trim_fraction)
        return vec
    if defense_type == "three_sigma":
        # host parity: score_i = ||u_i - coord_median||; keep within
        # median(score) + 3 * 1.4826 * MAD(score)
        med = jnp.median(mat_s, axis=0)
        part = jnp.sum((mat_s - med[None]) ** 2, axis=1)
        scores = jnp.sqrt(jax.lax.psum(part, axis))
        mu = jnp.median(scores)
        sd = 1.4826 * jnp.median(jnp.abs(scores - mu)) + 1e-12
        keep = (scores <= mu + 3.0 * sd).astype(weights.dtype)
        return robust_agg.weighted_mean(mat_s, weights * keep)
    partial_d = robust_agg.pairwise_sq_dists(mat_s)
    dists = jax.lax.psum(partial_d, axis)
    sel_w = _selection_weights(defense_type, dists, weights,
                               byzantine_count, multi_k)
    return robust_agg.weighted_mean(mat_s, sel_w)


@lru_cache(maxsize=32)
def _build_sharded_fn(mesh: Mesh, axis: str, defense_type: str,
                      byzantine_count: int, multi_k: int,
                      trim_fraction: float,
                      attack_type: Optional[str] = None,
                      attack_scale: float = 1.0):
    """One compiled kernel per (mesh, defense, params); jit re-traces only
    on new shapes — without this cache every round would recompile."""

    def body(mat_s, weights, byz_mask, key):
        # mat_s: [K, D/n] local shard
        if attack_type is not None:
            mat_s = _apply_attack_shard(attack_type, mat_s, byz_mask, key,
                                        attack_scale, axis)
        return defend_shard(mat_s, weights, axis, defense_type,
                            byzantine_count, multi_k, trim_fraction)

    return jax.jit(shard_map(
        body, mesh=mesh,
        in_specs=(P(None, axis), P(), P(), P()),
        out_specs=P(axis),
        check_vma=False,
    ))


def supports_sharded(defense_type: str) -> bool:
    return defense_type in _SHARDED


def _selection_weights(defense_type: str, dists: jnp.ndarray,
                       weights: jnp.ndarray, byzantine_count: int,
                       multi_k: int) -> jnp.ndarray:
    """[K] aggregation weights from the replicated [K, K] distance matrix."""
    k = dists.shape[0]
    if defense_type in ("krum", "multi_krum"):
        m = 1 if defense_type == "krum" else multi_k
        closest = max(k - byzantine_count - 2, 1)
        sorted_d = jnp.sort(dists, axis=1)
        scores = jnp.sum(sorted_d[:, 1:closest + 1], axis=1)
        order = jnp.argsort(scores)
        sel = jnp.zeros(k).at[order[:m]].set(1.0)
        return sel * weights
    return weights  # mean


def defend_matrix_sharded(
    mesh: Mesh,
    axis: str,
    mat: jnp.ndarray,
    weights: jnp.ndarray,
    defense_type: str,
    byzantine_count: int = 0,
    multi_k: int = 1,
    trim_fraction: float = 0.1,
    attack_type: Optional[str] = None,
    attack_scale: float = 1.0,
    byz_mask: Optional[jnp.ndarray] = None,
    attack_key: Optional[jax.Array] = None,
) -> jnp.ndarray:
    """[K, D] (feature-sharded over ``axis``) -> defended aggregate [D]
    (feature-sharded). The caller owns placement; this never gathers D.
    When ``attack_type`` is set, model poisoning is injected ON DEVICE on
    the sharded matrix before the defense (the adversarial-evaluation
    pipeline without any host round-trip)."""
    if not supports_sharded(defense_type):
        raise ValueError(f"{defense_type!r} has no sharded path; host "
                         f"fallback required (supported: {_SHARDED})")

    fn = _build_sharded_fn(mesh, axis, defense_type, byzantine_count,
                           multi_k, float(trim_fraction),
                           attack_type, float(attack_scale))
    n = mesh.shape[axis]
    d = mat.shape[1]
    pad = (-d) % n
    if pad:
        mat = jnp.pad(mat, ((0, 0), (0, pad)))
    mat = jax.device_put(mat, NamedSharding(mesh, P(None, axis)))
    k = mat.shape[0]
    if byz_mask is None:
        byz_mask = jnp.zeros(k, jnp.float32)
    if attack_key is None:
        attack_key = jax.random.PRNGKey(0)
    out = fn(mat, jnp.asarray(weights, jnp.float32),
             jnp.asarray(byz_mask, jnp.float32), attack_key)
    return out[:d]
