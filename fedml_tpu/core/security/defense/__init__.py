"""Defense dispatch — the ``FedMLDefender`` singleton of the reference
(``core/security/fedml_defender.py:40``; stage dispatch :152-184) rebuilt
around pure jit-able kernels (:mod:`.robust_agg`).

The defender consumes the round's *stacked* client updates (a pytree whose
leaves carry a leading [K] client axis) + weights, and returns the defended
aggregate update. Geometry defenses run on the flattened [K, D] matrix; the
flatten/unflatten is shape-driven and jit-compatible. Host-side state
(FoolsGold history, cclip momentum, previous global) lives on the instance
between rounds, mirroring the reference's stateful defense objects.
"""

from __future__ import annotations

from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ...collectives import vector_to_tree_like
from ....utils.confval import get_float, get_int
from . import robust_agg

PyTree = Any

DEFENSE_TYPES = (
    "krum", "multi_krum", "bulyan", "coordinate_median", "median",
    "trimmed_mean", "rfa", "geometric_median", "norm_clip", "cclip",
    "weak_dp", "crfl", "foolsgold", "three_sigma", "outlier_detection",
    "residual_reweight", "slsgd", "robust_learning_rate", "rlr",
    "soteria", "wbc", "cross_round",
)


def stack_to_matrix(stacked: PyTree) -> jnp.ndarray:
    """[K, ...]-leaved pytree -> [K, D] matrix."""
    leaves = jax.tree_util.tree_leaves(stacked)
    k = leaves[0].shape[0]
    return jnp.concatenate(
        [jnp.reshape(l, (k, -1)).astype(jnp.float32) for l in leaves], axis=1)


def verdict_from_info(info, k: int) -> Optional[np.ndarray]:
    """Map a host defense kernel's info dict to the [K] per-client verdict
    the selection subsystem consumes (selection masks / keep flags /
    continuous weights). None when the defense exposes no per-client
    notion — reputation then simply sees no evidence this round.

    Semantic guard: ``selected``/``kept`` must be BINARY masks — host
    bulyan's ``selected`` carries top-theta row INDICES, which would pass
    a shape-only check (theta == k when byzantine_count == 0) and brand
    arbitrary clients. Continuous keys must already live in [0, 1]."""
    if not isinstance(info, dict):
        return None
    for key, binary in (("selected", True), ("kept", True),
                        ("fg_weights", False), ("confidence", False)):
        v = info.get(key)
        if v is None:
            continue
        v = np.asarray(v, np.float32)
        if v.shape != (k,):
            continue
        if binary and not np.all((v == 0.0) | (v == 1.0)):
            continue  # an index list, not an inclusion mask
        if not binary and (np.min(v) < 0.0 or np.max(v) > 1.0):
            continue
        return v
    return None


class FedMLDefender:
    """Configured from args; applied by engines/aggregators when
    ``args.enable_defense`` (stage semantics of the reference's
    before/on/after-aggregation hooks collapse into one call here, since the
    kernels fuse selection + aggregation)."""

    _instance: Optional["FedMLDefender"] = None

    def __init__(self, args):
        self.args = args
        self.defense_type = str(getattr(args, "defense_type", None) or "").lower()
        self.enabled = bool(getattr(args, "enable_defense", False)) and \
            self.defense_type in DEFENSE_TYPES
        self.byzantine_count = get_int(args, "byzantine_client_num", 0)
        self.krum_param_m = get_int(args, "krum_param_m", 1)
        self.trim_fraction = get_float(args, "beta", 0.1)
        self.norm_bound = get_float(args, "norm_bound", 5.0)
        self.cclip_tau = get_float(args, "tau", 10.0)
        self.dp_stddev = get_float(args, "stddev", 0.002)
        self.alpha = get_float(args, "alpha", 1.0)
        self.rfa_iters = get_int(args, "rfa_iters", 8)
        # rfa_tol > 0: convergence-based Weiszfeld early exit (rfa_iters
        # becomes a budget, not a trip count); 0 keeps the fixed count —
        # the bit-parity default vs the sharded kernel
        self.rfa_tol = get_float(args, "rfa_tol", 0.0)
        # host-side cross-round state
        self._fg_history: Optional[np.ndarray] = None
        self._cclip_momentum = None
        self._prev_global = None
        self._round = 0

    # --- reference-compatible singleton access -----------------------------
    @classmethod
    def get_instance(cls, args=None) -> "FedMLDefender":
        if args is not None or cls._instance is None:
            cls._instance = cls(args)
        return cls._instance

    def is_defense_enabled(self) -> bool:
        return self.enabled

    # -----------------------------------------------------------------------
    def defend_matrix(
        self,
        mat: jnp.ndarray,
        weights: jnp.ndarray,
        rng: Optional[jax.Array] = None,
        client_ids: Optional[np.ndarray] = None,
    ) -> Tuple[jnp.ndarray, Dict]:
        """[K, D] update matrix -> defended aggregate vector [D]. The entry
        point engines use (both simulators flatten their stacked updates to
        the same matrix layout, which keeps SP/TPU parity a property of one
        code path)."""
        rng = rng if rng is not None else jax.random.PRNGKey(self._round)
        vec, info = self._dispatch(mat, jnp.asarray(weights, jnp.float32), rng,
                                   client_ids)
        self._round += 1
        return vec, info

    def defend(
        self,
        stacked_update: PyTree,
        weights: jnp.ndarray,
        rng: Optional[jax.Array] = None,
        client_ids: Optional[np.ndarray] = None,
    ) -> Tuple[PyTree, Dict]:
        """Stacked client updates -> defended aggregate update (pytree)."""
        template = jax.tree_util.tree_map(lambda l: l[0], stacked_update)
        mat = stack_to_matrix(stacked_update)
        vec, info = self.defend_matrix(mat, weights, rng, client_ids)
        return vector_to_tree_like(vec, template), info

    def _dispatch(self, mat, weights, rng, client_ids):
        d = self.defense_type
        if d == "krum":
            return robust_agg.krum(mat, weights, self.byzantine_count, 1)
        if d == "multi_krum":
            return robust_agg.krum(mat, weights, self.byzantine_count,
                                   self.krum_param_m)
        if d == "bulyan":
            return robust_agg.bulyan(mat, weights, self.byzantine_count)
        if d in ("coordinate_median", "median"):
            return robust_agg.coordinate_median(mat, weights)
        if d == "trimmed_mean":
            return robust_agg.trimmed_mean(mat, weights, self.trim_fraction)
        if d in ("rfa", "geometric_median"):
            return robust_agg.geometric_median(mat, weights,
                                               iters=self.rfa_iters,
                                               tol=self.rfa_tol)
        if d == "norm_clip":
            return robust_agg.norm_clip(mat, weights, self.norm_bound)
        if d == "cclip":
            out, info = robust_agg.centered_clip(
                mat, weights, self.cclip_tau, momentum=self._cclip_momentum)
            self._cclip_momentum = out
            return out, info
        if d == "weak_dp":
            return robust_agg.weak_dp(mat, weights, rng, self.dp_stddev)
        if d == "crfl":
            agg = robust_agg.weighted_mean(mat, weights)
            return robust_agg.crfl_clip_and_perturb(
                agg, rng, self.norm_bound, self.dp_stddev), {}
        if d == "foolsgold":
            hist = self._update_fg_history(np.asarray(mat), client_ids)
            return robust_agg.foolsgold(mat, weights, jnp.asarray(hist))
        if d == "three_sigma":
            return robust_agg.three_sigma(mat, weights)
        if d == "outlier_detection":
            return robust_agg.outlier_detection(mat, weights)
        if d == "residual_reweight":
            return robust_agg.residual_reweight(mat, weights)
        if d == "slsgd":
            out, info = robust_agg.slsgd(
                mat, weights, trim_b=max(self.byzantine_count, 1),
                alpha=self.alpha, prev_global=self._prev_global)
            self._prev_global = out
            return out, info
        if d in ("robust_learning_rate", "rlr"):
            return robust_agg.robust_learning_rate(mat, weights)
        if d == "soteria":
            return robust_agg.soteria(mat, weights,
                                      get_float(self.args, "soteria_frac",
                                                0.5))
        if d == "wbc":
            return robust_agg.wbc(mat, weights)
        if d == "cross_round":
            prev, has_prev = self._cross_round_state(np.asarray(mat),
                                                     client_ids)
            return robust_agg.cross_round_filter(
                mat, weights, jnp.asarray(prev), jnp.asarray(has_prev),
                get_float(self.args, "cross_round_threshold", -0.5))
        raise ValueError(f"unknown defense_type {self.defense_type!r}")

    def _cross_round_state(self, mat: np.ndarray, client_ids):
        """Per-client previous-round updates for the cross-round defense
        (keyed by true client id; absent history passes through)."""
        if client_ids is None:
            client_ids = np.arange(mat.shape[0])
        if not hasattr(self, "_cr_prev"):
            self._cr_prev = {}
        prev = np.zeros_like(mat)
        has = np.zeros(mat.shape[0], np.float32)
        for row, cid in enumerate(np.asarray(client_ids)):
            if int(cid) in self._cr_prev:
                prev[row] = self._cr_prev[int(cid)]
                has[row] = 1.0
        for row, cid in enumerate(np.asarray(client_ids)):
            self._cr_prev[int(cid)] = mat[row]
        return prev, has

    def _update_fg_history(self, mat: np.ndarray, client_ids) -> np.ndarray:
        """FoolsGold needs per-client *accumulated* history across rounds."""
        if client_ids is None:
            client_ids = np.arange(mat.shape[0])
        n_total = int(getattr(self.args, "client_num_in_total", mat.shape[0]))
        if self._fg_history is None:
            self._fg_history = np.zeros((n_total, mat.shape[1]), np.float32)
        self._fg_history[np.asarray(client_ids)] += mat
        return self._fg_history[np.asarray(client_ids)]
