"""Topology managers for decentralized FL (reference
``core/distributed/topology/``: ``base_topology_manager.py:4``,
``symmetric_topology_manager.py:7``, ``asymmetric_topology_manager.py:7``).

A topology is an [n, n] row-stochastic mixing matrix; neighbor lists derive
from its sparsity. The TPU engine consumes topologies as ``ppermute``
source-target pairs / weighted neighbor psums (``collectives.ppermute_tree``).
"""

from .base_topology_manager import BaseTopologyManager
from .symmetric_topology_manager import SymmetricTopologyManager
from .asymmetric_topology_manager import AsymmetricTopologyManager

__all__ = ["BaseTopologyManager", "SymmetricTopologyManager",
           "AsymmetricTopologyManager"]
