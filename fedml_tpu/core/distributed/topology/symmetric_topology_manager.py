"""Symmetric (undirected) topologies (reference
``symmetric_topology_manager.py:7``): ring with ``neighbor_num`` hops each
side plus optional random extra edges, symmetrized, rows normalized."""

from __future__ import annotations

import numpy as np

from .base_topology_manager import BaseTopologyManager


class SymmetricTopologyManager(BaseTopologyManager):
    def __init__(self, n: int, neighbor_num: int = 2, seed: int = 0):
        self.n = int(n)
        self.neighbor_num = int(neighbor_num)
        self.seed = seed
        self.topology = np.zeros((self.n, self.n))

    def generate_topology(self) -> None:
        n, k = self.n, self.neighbor_num
        adj = np.eye(n)
        for i in range(n):
            for h in range(1, k // 2 + 1):
                adj[i, (i + h) % n] = 1
                adj[i, (i - h) % n] = 1
        adj = np.maximum(adj, adj.T)  # symmetric
        self.topology = adj / adj.sum(axis=1, keepdims=True)

    def generate_custom_topology(self, adj: np.ndarray) -> None:
        adj = np.maximum(np.asarray(adj, float), np.asarray(adj, float).T)
        np.fill_diagonal(adj, 1.0)
        self.topology = adj / adj.sum(axis=1, keepdims=True)
