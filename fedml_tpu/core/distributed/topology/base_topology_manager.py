"""Base topology interface (reference ``base_topology_manager.py:4``)."""

from __future__ import annotations

from abc import ABC, abstractmethod
from typing import List

import numpy as np


class BaseTopologyManager(ABC):
    n: int
    topology: np.ndarray  # [n, n] row-stochastic mixing weights

    @abstractmethod
    def generate_topology(self) -> None:
        ...

    def get_in_neighbor_idx_list(self, node_index: int) -> List[int]:
        """Nodes whose values flow INTO ``node_index`` (nonzero column)."""
        col = self.topology[:, node_index]
        return [i for i in range(self.n)
                if col[i] > 0 and i != node_index]

    def get_out_neighbor_idx_list(self, node_index: int) -> List[int]:
        row = self.topology[node_index]
        return [i for i in range(self.n)
                if row[i] > 0 and i != node_index]

    def get_in_neighbor_weights(self, node_index: int) -> List[float]:
        return list(self.topology[:, node_index])

    def get_out_neighbor_weights(self, node_index: int) -> List[float]:
        return list(self.topology[node_index])

    def mixing_matrix(self) -> np.ndarray:
        return self.topology

    def to_ppermute_pairs(self) -> List[tuple]:
        """(src, dst) pairs for ``jax.lax.ppermute`` — one pair per directed
        edge (excluding self-loops)."""
        pairs = []
        for i in range(self.n):
            for j in self.get_out_neighbor_idx_list(i):
                pairs.append((i, j))
        return pairs
