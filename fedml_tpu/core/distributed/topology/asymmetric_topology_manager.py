"""Asymmetric (directed) topologies (reference
``asymmetric_topology_manager.py:7``): directed ring + random out-edges,
rows normalized (column sums unconstrained)."""

from __future__ import annotations

import numpy as np

from .base_topology_manager import BaseTopologyManager


class AsymmetricTopologyManager(BaseTopologyManager):
    def __init__(self, n: int, neighbor_num: int = 2, seed: int = 0):
        self.n = int(n)
        self.neighbor_num = int(neighbor_num)
        self.seed = seed
        self.topology = np.zeros((self.n, self.n))

    def generate_topology(self) -> None:
        n = self.n
        rng = np.random.RandomState(self.seed)
        adj = np.eye(n)
        for i in range(n):
            adj[i, (i + 1) % n] = 1  # directed ring
            extra = rng.choice(n, size=max(self.neighbor_num - 1, 0),
                               replace=False)
            for j in extra:
                adj[i, j] = 1
        self.topology = adj / adj.sum(axis=1, keepdims=True)
