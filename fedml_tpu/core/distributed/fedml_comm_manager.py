"""FedMLCommManager — the event-loop base class of every WAN manager.

Parity target: reference ``core/distributed/fedml_comm_manager.py:11``
(``register_message_receive_handler`` :63, ``send_message`` :53, ``run`` :25,
backend factory ``_init_manager`` :131). Backends here: INPROC (threaded
tests/sims), TCP, GRPC — the reference's MQTT_S3/MPI/TRPC fill the same
role; MQTT needs paho (not in this environment) and is stubbed with a clear
error.
"""

from __future__ import annotations

import logging
from typing import Callable, Dict, Optional

from .communication.base_com_manager import BaseCommunicationManager, Observer
from .communication.message import Message

logger = logging.getLogger(__name__)


class FedMLCommManager(Observer):
    def __init__(self, args, comm=None, rank: int = 0, size: int = 0,
                 backend: str = "INPROC"):
        self.args = args
        self.size = size
        self.rank = int(rank)
        self.backend = backend.upper()
        self.com_manager: Optional[BaseCommunicationManager] = comm
        self.message_handler_dict: Dict[object, Callable] = {}
        if self.com_manager is None:
            self.com_manager = self._init_manager()
            # chaos interceptor at the Message send seam: only when link
            # faults are configured (default off → the transport object
            # and the wire are exactly what they were), and only around
            # managers WE built — an externally shared comm object may
            # already be wrapped by its owner
            from ..chaos import ChaosCommManager, FaultPlan
            plan = FaultPlan.from_args(args)
            if plan.injects_link_faults:
                self.com_manager = ChaosCommManager(self.com_manager, plan,
                                                    self.rank)
        self.com_manager.add_observer(self)

    # --- reference-compatible surface ---------------------------------------
    def register_comm_manager(self, comm: BaseCommunicationManager) -> None:
        self.com_manager = comm

    def run(self) -> None:
        self.register_message_receive_handlers()
        logger.info("rank %d (%s) entering receive loop", self.rank,
                    type(self).__name__)
        self.com_manager.handle_receive_message()
        logger.info("rank %d receive loop done", self.rank)

    def get_sender_id(self) -> int:
        return self.rank

    def receive_message(self, msg_type, msg: Message) -> None:
        handler = self.message_handler_dict.get(msg_type)
        if handler is None:
            logger.warning("rank %d: no handler for msg_type %r", self.rank,
                           msg_type)
            return
        handler(msg)

    def send_message(self, message: Message) -> None:
        self.com_manager.send_message(message)

    def register_message_receive_handlers(self) -> None:
        """Subclasses register their FSM here."""

    def register_message_receive_handler(self, msg_type,
                                         handler: Callable) -> None:
        self.message_handler_dict[msg_type] = handler

    def finish(self) -> None:
        logger.info("rank %d finishing", self.rank)
        self.com_manager.stop_receive_message()

    # --- backend factory ----------------------------------------------------
    def _init_manager(self) -> BaseCommunicationManager:
        b = self.backend
        if b == "INPROC":
            broker = getattr(self.args, "inproc_broker", None)
            if broker is None:
                raise ValueError("INPROC backend needs args.inproc_broker")
            from .communication.inproc import InProcCommManager
            return InProcCommManager(broker, self.rank)
        if b == "TCP":
            from .communication.backoff import retry_policy_from_args
            from .communication.tcp import TCPCommManager
            return TCPCommManager(self.rank,
                                  getattr(self.args, "ip_config", None),
                                  int(getattr(self.args, "tcp_base_port", 0)
                                      or 29690),
                                  retry=retry_policy_from_args(self.args))
        if b == "GRPC":
            from .communication.backoff import retry_policy_from_args
            from .communication.grpc import GRPCCommManager
            return GRPCCommManager(self.rank,
                                   getattr(self.args, "ip_config", None),
                                   int(getattr(self.args, "grpc_base_port", 0)
                                       or 29790),
                                   retry=retry_policy_from_args(self.args))
        if b in ("PUBSUB", "PUBSUB_STORAGE", "MQTT_S3_LOCAL"):
            from .communication.pubsub import PubSubStorageCommManager
            port = int(getattr(self.args, "pubsub_broker_port", 0) or 0)
            if port <= 0:
                raise ValueError(
                    "backend PUBSUB needs args.pubsub_broker_port (the "
                    "port of a running PubSubBroker; start one with "
                    "fedml_tpu.core.distributed.communication.pubsub."
                    "PubSubBroker())")
            return PubSubStorageCommManager(
                self.rank,
                broker_host=str(getattr(self.args, "pubsub_broker_host",
                                        "127.0.0.1")),
                broker_port=port,
                run_id=str(getattr(self.args, "run_id", "0")))
        if b == "TRPC":
            from .communication.trpc import TRPCCommManager
            return TRPCCommManager(
                self.rank, self.size,
                master_addr=str(getattr(self.args, "trpc_master_addr",
                                        "127.0.0.1")),
                master_port=int(getattr(self.args, "trpc_master_port", 0)
                                or 29500))
        if b == "MPI":
            raise ImportError(
                "MPI backend needs mpi4py + an MPI runtime (absent here); "
                "INPROC covers the simulation role, TCP/GRPC/TRPC the "
                "distributed one")
        if b in ("MQTT_S3", "MQTT_WEB3", "MQTT_THETASTORE", "MQTT_S3_MNN"):
            raise ImportError(
                f"backend {b} needs paho-mqtt (not available in this "
                "environment); PUBSUB provides the same control/data-plane "
                "split (broker topics + object-store payloads) over stdlib "
                "TCP, or use GRPC/TCP")
        raise ValueError(f"unknown comm backend {b!r}")
