"""TRPC transport — torch.distributed.rpc (TensorPipe) backend.

Parity target: reference ``communication/trpc/trpc_comm_manager.py:21``
(``rpc.init_rpc`` :66, ``rpc_sync`` :82 into the peer's message handler).
The wire payload is the same msgpack ``Message`` encoding as TCP/gRPC
(the reference sends pickled objects over RPC; msgpack keeps the payload
engine-neutral and safe), so managers are drop-in interchangeable.

``rpc.init_rpc`` is process-global — exactly one TRPCCommManager per
process (the reference has the same constraint); multi-rank tests therefore
run one rank per spawned process. Coordination uses the torchrun env
contract (MASTER_ADDR/MASTER_PORT).
"""

from __future__ import annotations

import logging
import os
import queue
import threading
from typing import Optional

from ..base_com_manager import BaseCommunicationManager
from ..message import Message

logger = logging.getLogger(__name__)

_WORKER_FMT = "fedml_tpu_worker_{}"

# process-global inbox the RPC target function drops into (rpc functions
# must be module-level importables on the callee)
_INBOX: "queue.Queue[bytes]" = queue.Queue()


def _deliver(blob: bytes) -> bool:
    _INBOX.put(bytes(blob))
    return True


class TRPCCommManager(BaseCommunicationManager):
    def __init__(self, rank: int, world_size: int,
                 master_addr: str = "127.0.0.1",
                 master_port: int = 29500,
                 num_threads: int = 4):
        super().__init__()
        import torch.distributed.rpc as rpc

        self.rank = int(rank)
        self.world_size = int(world_size)
        self._rpc = rpc
        os.environ.setdefault("MASTER_ADDR", master_addr)
        os.environ.setdefault("MASTER_PORT", str(master_port))
        opts = rpc.TensorPipeRpcBackendOptions(num_worker_threads=num_threads)
        rpc.init_rpc(_WORKER_FMT.format(self.rank), rank=self.rank,
                     world_size=self.world_size, rpc_backend_options=opts)
        self._running = False
        logger.info("trpc rank %d/%d up", self.rank, self.world_size)

    def send_message(self, msg: Message) -> None:
        dst = _WORKER_FMT.format(int(msg.get_receiver_id()))
        self._rpc.rpc_sync(dst, _deliver, args=(msg.encode(),))

    def handle_receive_message(self) -> None:
        self._running = True
        while self._running:
            try:
                blob = _INBOX.get(timeout=0.2)
            except queue.Empty:
                continue
            self.notify(Message.decode(blob))

    def stop_receive_message(self) -> None:
        self._running = False
        try:
            self._rpc.shutdown(graceful=False)
        except Exception:  # noqa: BLE001 — already down
            pass
