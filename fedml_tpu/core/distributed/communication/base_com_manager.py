"""Transport abstraction (reference
``core/distributed/communication/base_com_manager.py:7`` +
``observer.py:4``): every backend (in-proc, TCP, gRPC) implements
``BaseCommunicationManager``; managers register as ``Observer``s and get a
callback per received message."""

from __future__ import annotations

from abc import ABC, abstractmethod
from typing import List

from .message import Message


class Observer(ABC):
    @abstractmethod
    def receive_message(self, msg_type, msg: Message) -> None:
        ...


class BaseCommunicationManager(ABC):
    def __init__(self):
        self._observers: List[Observer] = []

    def add_observer(self, observer: Observer) -> None:
        self._observers.append(observer)

    def remove_observer(self, observer: Observer) -> None:
        if observer in self._observers:
            self._observers.remove(observer)

    def notify(self, msg: Message) -> None:
        for obs in list(self._observers):
            obs.receive_message(msg.get_type(), msg)

    @abstractmethod
    def send_message(self, msg: Message) -> None:
        ...

    @abstractmethod
    def handle_receive_message(self) -> None:
        """Block, dispatching received messages to observers, until
        :meth:`stop_receive_message`."""
        ...

    @abstractmethod
    def stop_receive_message(self) -> None:
        ...
