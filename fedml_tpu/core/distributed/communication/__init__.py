from .message import Message, tree_to_wire, wire_to_tree
from .base_com_manager import BaseCommunicationManager, Observer

__all__ = ["Message", "tree_to_wire", "wire_to_tree",
           "BaseCommunicationManager", "Observer"]
