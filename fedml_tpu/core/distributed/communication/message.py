"""Message envelope for the WAN FSM.

Parity target: reference ``core/distributed/communication/message.py:6-83``
(dict with ``msg_type``, ``sender``, ``receiver`` + payload; model params as
a field). The reference pickles torch state-dicts; here payloads are
msgpack-serialized with an explicit numpy-array extension — no pickle on the
wire (pickle is both unsafe and torch-coupled), and jax arrays cross as
numpy + dtype + shape.
"""

from __future__ import annotations

import threading
from typing import Any, Dict

import msgpack
import numpy as np

from ...obs import metrics as obs_metrics


class WireStats:
    """Bytes-on-wire ledger at the encode seam: every ``Message.encode``
    records its serialized size under the message type, so any transport
    (in-proc, TCP, gRPC, pub/sub) gets per-message-type accounting for
    free. Thread-safe; one process-wide instance (``WIRE_STATS``) because
    a process is one rank — readers diff :meth:`snapshot` across rounds."""

    def __init__(self):
        self._lock = threading.Lock()
        self._by_type: Dict[Any, Dict[str, int]] = {}
        # per-pipeline-stage byte attribution (core/wire): msg_type ->
        # stage name -> bytes. Stages are recorded by the pipeline
        # (raw / sparsified / masked); framing totals live in by_type.
        self._by_stage: Dict[Any, Dict[str, int]] = {}
        self._total_bytes = 0
        self._total_msgs = 0

    def record(self, msg_type: Any, nbytes: int) -> None:
        with self._lock:
            ent = self._by_type.setdefault(msg_type,
                                           {"bytes": 0, "messages": 0})
            ent["bytes"] += int(nbytes)
            ent["messages"] += 1
            self._total_bytes += int(nbytes)
            self._total_msgs += 1
        # the same seam feeds the typed metrics registry (per-message-type
        # wire bytes counters — core/obs/metrics); outside the lock, the
        # registry has its own
        obs_metrics.record_wire(msg_type, nbytes)

    def record_stage(self, msg_type: Any, stage: str, nbytes: int) -> None:
        """Attribute bytes to one wire-pipeline stage (``core/wire``) for
        a message type — the where-did-the-bytes-go ledger behind the
        framed totals in :meth:`record`."""
        with self._lock:
            ent = self._by_stage.setdefault(msg_type, {})
            ent[stage] = ent.get(stage, 0) + int(nbytes)
        obs_metrics.record_wire_stage(msg_type, stage, nbytes)

    def snapshot(self) -> Dict[str, Any]:
        with self._lock:
            return {"total_bytes": self._total_bytes,
                    "total_messages": self._total_msgs,
                    "by_type": {str(t): dict(v)
                                for t, v in self._by_type.items()},
                    "by_stage": {str(t): dict(v)
                                 for t, v in self._by_stage.items()}}

    @property
    def total_bytes(self) -> int:
        with self._lock:
            return self._total_bytes

    def reset(self) -> None:
        with self._lock:
            self._by_type.clear()
            self._by_stage.clear()
            self._total_bytes = 0
            self._total_msgs = 0


WIRE_STATS = WireStats()


class Message:
    # canonical keys (reference message.py constants)
    MSG_ARG_KEY_TYPE = "msg_type"
    MSG_ARG_KEY_SENDER = "sender"
    MSG_ARG_KEY_RECEIVER = "receiver"
    MSG_ARG_KEY_MODEL_PARAMS = "model_params"
    MSG_ARG_KEY_MODEL_PARAMS_URL = "model_params_url"
    MSG_ARG_KEY_NUM_SAMPLES = "num_samples"
    MSG_ARG_KEY_CLIENT_INDEX = "client_idx"
    MSG_ARG_KEY_CLIENT_STATUS = "client_status"
    # W3C trace-context header (core/obs/trace.inject/extract): an
    # ordinary payload param, so EVERY transport propagates it for free
    MSG_ARG_KEY_TRACEPARENT = "traceparent"

    def __init__(self, msg_type: Any = 0, sender_id: int = 0,
                 receiver_id: int = 0):
        self.msg_params: Dict[str, Any] = {
            Message.MSG_ARG_KEY_TYPE: msg_type,
            Message.MSG_ARG_KEY_SENDER: sender_id,
            Message.MSG_ARG_KEY_RECEIVER: receiver_id,
        }

    # --- reference-compatible accessors ------------------------------------
    def get_sender_id(self) -> int:
        return self.msg_params[Message.MSG_ARG_KEY_SENDER]

    def get_receiver_id(self) -> int:
        return self.msg_params[Message.MSG_ARG_KEY_RECEIVER]

    def get_type(self):
        return self.msg_params[Message.MSG_ARG_KEY_TYPE]

    def add_params(self, key: str, value: Any) -> None:
        self.msg_params[key] = value

    add = add_params

    def get_params(self) -> Dict[str, Any]:
        return self.msg_params

    def get(self, key: str, default: Any = None) -> Any:
        return self.msg_params.get(key, default)

    def __repr__(self) -> str:
        keys = ", ".join(sorted(self.msg_params))
        return (f"Message(type={self.get_type()!r}, "
                f"{self.get_sender_id()}->{self.get_receiver_id()}, "
                f"keys=[{keys}])")

    # --- wire format --------------------------------------------------------
    def encode(self) -> bytes:
        blob = msgpack.packb(self.msg_params, default=_pack_np,
                             use_bin_type=True)
        WIRE_STATS.record(self.get_type(), len(blob))
        return blob

    @classmethod
    def decode(cls, blob: bytes) -> "Message":
        params = msgpack.unpackb(blob, ext_hook=_unpack_np, raw=False,
                                 strict_map_key=False)
        msg = cls()
        msg.msg_params = params
        return msg


_NP_EXT = 42


def _pack_np(obj):
    """msgpack hook: numpy/jax arrays -> ext(dtype, shape, bytes)."""
    if hasattr(obj, "__array__"):  # numpy array or jax array
        arr = np.ascontiguousarray(np.asarray(obj))
        head = msgpack.packb((arr.dtype.str, list(arr.shape)))
        return msgpack.ExtType(_NP_EXT, head + arr.tobytes())
    if isinstance(obj, (np.integer,)):
        return int(obj)
    if isinstance(obj, (np.floating,)):
        return float(obj)
    raise TypeError(f"cannot serialize {type(obj)}")


def _unpack_np(code, data):
    if code != _NP_EXT:
        return msgpack.ExtType(code, data)
    unpacker = msgpack.Unpacker(use_list=True, raw=False)
    unpacker.feed(data)
    dtype_str, shape = unpacker.unpack()
    off = unpacker.tell()
    arr = np.frombuffer(data[off:], dtype=np.dtype(dtype_str))
    return arr.reshape(shape)


def dumps_tree(tree) -> bytes:
    """Serialize a pytree of arrays (nested dicts/lists — the flax param
    shape) with the wire codec. The single safe-serialization seam shared
    by messages, model artifacts, and the object store — never pickle."""
    import jax
    host = jax.tree_util.tree_map(np.asarray, jax.device_get(tree))
    return msgpack.packb(host, default=_pack_np, use_bin_type=True)


def loads_tree(blob: bytes) -> Any:
    return msgpack.unpackb(blob, ext_hook=_unpack_np, raw=False,
                           strict_map_key=False)


def tree_to_wire(tree) -> Dict[str, Any]:
    """Flatten a pytree of arrays into {path: np.ndarray} for a Message
    payload (the analogue of shipping a state-dict)."""
    import jax
    flat = {}
    for path, leaf in jax.tree_util.tree_flatten_with_path(tree)[0]:
        key = "/".join(str(getattr(p, "key", getattr(p, "idx", p)))
                       for p in path)
        flat[key] = np.asarray(leaf)
    return flat


WIRE_DTYPE_BF16 = "bf16"


def tree_to_wire_bf16(tree) -> Dict[str, Any]:
    """Half-width variant of :func:`tree_to_wire`: leaves cross as the
    uint16 bit pattern of their bfloat16 rounding (ml_dtypes' bfloat16 has
    dtype.str ``<V2``, which the numpy ext codec cannot round-trip — the
    bit view is codec-neutral). Tag the message with
    ``WIRE_DTYPE_BF16`` so the receiver knows to reinterpret."""
    import jax.numpy as jnp
    flat = tree_to_wire(tree)
    bf16 = np.dtype(jnp.bfloat16)
    return {k: np.asarray(v, bf16).view(np.uint16) for k, v in flat.items()}


def bf16_wire_to_tree(flat: Dict[str, Any], template):
    """Inverse of :func:`tree_to_wire_bf16`; leaves come back as the
    template's dtype (float32 weights widen from the bf16 rounding)."""
    import jax.numpy as jnp
    bf16 = np.dtype(jnp.bfloat16)
    widened = {k: np.asarray(np.asarray(v, np.uint16).view(bf16))
               for k, v in flat.items()}
    tree = wire_to_tree(widened, template)
    import jax
    return jax.tree_util.tree_map(
        lambda leaf, t: np.asarray(leaf, np.asarray(t).dtype), tree, template)


def wire_to_tree(flat: Dict[str, Any], template):
    """Inverse of :func:`tree_to_wire` given a structural template."""
    import jax
    paths_leaves = jax.tree_util.tree_flatten_with_path(template)
    keys = ["/".join(str(getattr(p, "key", getattr(p, "idx", p)))
                     for p in path)
            for path, _ in paths_leaves[0]]
    leaves = [np.asarray(flat[k]) for k in keys]
    return jax.tree_util.tree_unflatten(paths_leaves[1], leaves)
