"""In-process broker transport — queues between threads in one process.

No reference counterpart (the reference's cheapest transport is MPI); this
backend exists because the TPU build runs cross-silo protocol tests without
a cluster (SURVEY §4 "multi-node-without-a-cluster"): every rank is a thread
and the broker routes encoded Messages between per-rank queues. Messages are
encode/decode round-tripped so the wire path is exercised identically to
TCP/gRPC.
"""

from __future__ import annotations

import queue
import threading
from typing import Dict

from ..base_com_manager import BaseCommunicationManager
from ..message import Message


class InProcBroker:
    """Shared router: one inbox per rank. Create one per simulated run."""

    def __init__(self):
        self._inboxes: Dict[int, "queue.Queue[bytes]"] = {}
        self._lock = threading.Lock()

    def inbox(self, rank: int) -> "queue.Queue[bytes]":
        with self._lock:
            return self._inboxes.setdefault(int(rank), queue.Queue())

    def post(self, rank: int, blob: bytes) -> None:
        self.inbox(rank).put(blob)


class InProcCommManager(BaseCommunicationManager):
    def __init__(self, broker: InProcBroker, rank: int):
        super().__init__()
        self.broker = broker
        self.rank = int(rank)
        self._running = False

    def send_message(self, msg: Message) -> None:
        self.broker.post(msg.get_receiver_id(), msg.encode())

    def handle_receive_message(self) -> None:
        self._running = True
        inbox = self.broker.inbox(self.rank)
        while self._running:
            try:
                blob = inbox.get(timeout=0.1)
            except queue.Empty:
                continue
            self.notify(Message.decode(blob))

    def stop_receive_message(self) -> None:
        self._running = False
