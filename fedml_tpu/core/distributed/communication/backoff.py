"""Exponential backoff + jitter — the ONE retry policy for every transport.

Before the chaos subsystem there was zero retry anywhere under
``communication/`` (a refused connect killed the send) and one hand-rolled
sleep loop in ``cross_silo/decentralized.py``; this module unifies both.
Full jitter (delay drawn uniformly in ``[0, base * factor**attempt]``,
AWS-style) de-synchronizes retry storms when many silos hit the same dead
server; the jitter stream is seeded so a chaos run's retry timing is as
reproducible as its fault schedule.
"""

from __future__ import annotations

import logging
import time
from typing import Callable, Iterator, Optional, Tuple, Type

import numpy as np

logger = logging.getLogger(__name__)


def backoff_delays(base_s: float = 0.2, factor: float = 2.0,
                   max_s: float = 2.0, jitter: bool = True,
                   seed: Optional[int] = None) -> Iterator[float]:
    """Infinite iterator of backoff delays: ``min(base * factor**k, max)``,
    full-jittered (uniform in ``(0, cap]``) unless ``jitter=False``."""
    rng = np.random.default_rng(seed)
    k = 0
    while True:
        cap = min(base_s * (factor ** k), max_s)
        yield float(rng.uniform(0.0, cap)) if jitter else cap
        if base_s * (factor ** k) < max_s:
            k += 1


def retry_with_backoff(
    fn: Callable[[], None],
    max_attempts: int = 4,
    base_s: float = 0.2,
    factor: float = 2.0,
    max_s: float = 2.0,
    deadline_s: Optional[float] = None,
    retry_on: Tuple[Type[BaseException], ...] = (Exception,),
    seed: Optional[int] = None,
    describe: str = "operation",
    sleep: Callable[[float], None] = time.sleep,
    on_retry: Optional[Callable[[int, float, BaseException], None]] = None,
):
    """Run ``fn`` with up to ``max_attempts`` retries after the first try
    (``max_attempts=0`` = fail fast, the pre-chaos behavior). Stops early
    when ``deadline_s`` (wall seconds from the first attempt) is already
    exceeded or would be by the next delay — the check counts time SPENT
    INSIDE ``fn`` too, so a slow failing call (connect timeout) cannot
    stretch the budget by arriving at the check late. Re-raises the last
    failure.

    ``on_retry(attempt, delay_s, exc)`` fires before each retry sleep —
    the observability seam: transports attach a span event per retry so
    a trace shows WHERE a round's wall time went when the wire flapped.
    Hook failures are swallowed (observability never breaks the send)."""
    delays = backoff_delays(base_s, factor, max_s, seed=seed)
    t0 = time.monotonic()
    attempt = 0
    while True:
        try:
            return fn()
        except retry_on as e:
            attempt += 1
            delay = next(delays)
            expired = (deadline_s is not None
                       and time.monotonic() - t0 + delay > deadline_s)
            if attempt > max_attempts or expired:
                raise
            logger.debug("%s failed (%s: %s); retry %d/%d in %.2fs",
                         describe, type(e).__name__, e, attempt,
                         max_attempts, delay)
            if on_retry is not None:
                try:
                    on_retry(attempt, delay, e)
                except Exception:
                    logger.debug("on_retry hook failed", exc_info=True)
            sleep(delay)


def retry_policy_from_args(args) -> dict:
    """The transport-level retry knobs (``comm_retry_*``) as kwargs for
    :func:`retry_with_backoff`; a single reading so TCP/gRPC/decentralized
    can't drift apart on defaults.

    ``comm_retry_deadline_s`` caps the TOTAL elapsed retry budget (wall
    seconds from the first attempt) on top of the attempt count: without
    it, a long per-try timeout times ``max_attempts`` can stall a caller
    — an async pour most of all — far past the point where retrying is
    useful. 0 (the default) keeps the legacy attempt-count-only bound."""
    deadline = float(getattr(args, "comm_retry_deadline_s", 0.0)
                     if args is not None else 0.0)
    return {
        "max_attempts": int(getattr(args, "comm_retry_max_attempts", 4)
                            if args is not None else 4),
        "base_s": float(getattr(args, "comm_retry_base_s", 0.2)
                        if args is not None else 0.2),
        "max_s": float(getattr(args, "comm_retry_max_s", 2.0)
                       if args is not None else 2.0),
        "deadline_s": deadline if deadline > 0 else None,
    }
