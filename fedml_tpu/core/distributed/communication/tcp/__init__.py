"""Raw-TCP transport — length-prefixed msgpack frames, one listener per rank.

Parity target: the role of the reference's gRPC backend
(``communication/grpc/grpc_comm_manager.py:30`` — every rank serves on
``base_port + rank``, peers connect ad-hoc to send) with the reference's
1 GB message ceiling replaced by streaming frames. The ip table maps rank ->
host (reference ``ip_config_utils.py`` reads a csv; here a dict or csv path).
"""

from __future__ import annotations

import logging
import queue
import socket
import struct
import threading
from typing import Dict, Optional

from ..base_com_manager import BaseCommunicationManager
from ..message import Message

logger = logging.getLogger(__name__)

TCP_BASE_PORT = 29690  # deliberately distinct from the reference's 8890


def _read_exact(sock: socket.socket, n: int) -> Optional[bytes]:
    buf = b""
    while len(buf) < n:
        chunk = sock.recv(min(n - len(buf), 1 << 20))
        if not chunk:
            return None
        buf += chunk
    return buf


class TCPCommManager(BaseCommunicationManager):
    """Listens on ``base_port + rank``; sends open a short-lived connection
    per message (WAN messages here are round-granularity, so connection
    reuse is not the bottleneck; model payloads stream in 1 MB chunks)."""

    def __init__(self, rank: int, ip_config: Optional[Dict[int, str]] = None,
                 base_port: int = TCP_BASE_PORT, host: str = "127.0.0.1",
                 retry: Optional[dict] = None):
        super().__init__()
        self.rank = int(rank)
        self.ip_config = ip_config or {}
        self.base_port = int(base_port)
        # transport retry policy (exponential backoff + jitter); pre-chaos
        # behavior — fail on the first refused connect — is retry
        # {"max_attempts": 0}
        self.retry = {"max_attempts": 4, "base_s": 0.2, "max_s": 2.0}
        self.retry.update(retry or {})
        self._q: "queue.Queue[bytes]" = queue.Queue()
        self._running = False
        self._srv = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._srv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._srv.bind((host, self.base_port + self.rank))
        self._srv.listen(64)
        self._accept_thread = threading.Thread(target=self._accept_loop,
                                               daemon=True)
        self._accept_thread.start()

    def _peer_addr(self, rank: int):
        return (self.ip_config.get(int(rank), "127.0.0.1"),
                self.base_port + int(rank))

    def _accept_loop(self) -> None:
        while True:
            try:
                conn, _ = self._srv.accept()
            except OSError:
                return  # socket closed
            threading.Thread(target=self._recv_one, args=(conn,),
                             daemon=True).start()

    def _recv_one(self, conn: socket.socket) -> None:
        try:
            head = _read_exact(conn, 8)
            if head is None:
                return
            (n,) = struct.unpack("!Q", head)
            blob = _read_exact(conn, n)
            if blob is not None:
                self._q.put(blob)
        finally:
            conn.close()

    def send_message(self, msg: Message) -> None:
        blob = msg.encode()
        addr = self._peer_addr(msg.get_receiver_id())

        def _send_once() -> None:
            with socket.create_connection(addr, timeout=30.0) as s:
                s.sendall(struct.pack("!Q", len(blob)))
                s.sendall(blob)

        from ....obs import trace as obs_trace
        from ..backoff import retry_with_backoff
        # the wire half of the trace: one span per send, backoff retries
        # attached as events — a flapping link shows up ON the round's
        # critical path instead of vanishing into the send call
        with obs_trace.span(
                "comm.send",
                attrs={"transport": "tcp",
                       "receiver": int(msg.get_receiver_id()),
                       "msg_type": str(msg.get_type()),
                       "bytes": len(blob)}) as sp:
            retry_with_backoff(
                _send_once, retry_on=(OSError,),
                describe=f"tcp send {self.rank}->{msg.get_receiver_id()}",
                on_retry=lambda a, d, e: sp.add_event(
                    "retry", attempt=a, delay_s=round(d, 4),
                    error=type(e).__name__),
                **self.retry)

    def handle_receive_message(self) -> None:
        self._running = True
        while self._running:
            try:
                blob = self._q.get(timeout=0.1)
            except queue.Empty:
                continue
            self.notify(Message.decode(blob))

    def stop_receive_message(self) -> None:
        self._running = False
        try:
            self._srv.close()
        except OSError:
            pass
