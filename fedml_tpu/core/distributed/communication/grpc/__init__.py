"""gRPC transport.

Parity target: reference ``communication/grpc/grpc_comm_manager.py:30``
(per-rank server on ``base_port + rank``, 1 GB max message, csv ip table).
Differences by design: the wire payload is the msgpack ``Message`` encoding
(not pickle — reference streams pickled objects, which is unsafe), and the
service is registered with grpcio's generic handler API so no protoc-
generated stubs are needed (the reference ships ``*_pb2.py``).
"""

from __future__ import annotations

import logging
import queue
import threading
from concurrent import futures
from typing import Dict, Optional

import grpc

from ..base_com_manager import BaseCommunicationManager
from ..message import Message

logger = logging.getLogger(__name__)

GRPC_BASE_PORT = 29790
_SERVICE = "fedml_tpu.Comm"
_METHOD = "SendMessage"
MAX_MSG = 1024 * 1024 * 1024  # 1 GB, matching reference constants.py:55-57


class GRPCCommManager(BaseCommunicationManager):
    def __init__(self, rank: int, ip_config: Optional[Dict[int, str]] = None,
                 base_port: int = GRPC_BASE_PORT, host: str = "127.0.0.1",
                 retry: Optional[dict] = None):
        super().__init__()
        self.rank = int(rank)
        self.ip_config = ip_config or {}
        self.base_port = int(base_port)
        # transport retry policy (exponential backoff + jitter); 0
        # attempts restores the pre-chaos fail-fast behavior
        self.retry = {"max_attempts": 4, "base_s": 0.2, "max_s": 2.0}
        self.retry.update(retry or {})
        self._q: "queue.Queue[bytes]" = queue.Queue()
        self._running = False
        self._channels: Dict[int, grpc.Channel] = {}

        def handler(request: bytes, context) -> bytes:
            self._q.put(request)
            return b"ok"

        rpc = grpc.unary_unary_rpc_method_handler(
            handler,
            request_deserializer=lambda b: b,
            response_serializer=lambda b: b)
        generic = grpc.method_handlers_generic_handler(
            _SERVICE, {_METHOD: rpc})
        self._server = grpc.server(
            futures.ThreadPoolExecutor(max_workers=8),
            options=[("grpc.max_send_message_length", MAX_MSG),
                     ("grpc.max_receive_message_length", MAX_MSG)])
        self._server.add_generic_rpc_handlers((generic,))
        self._server.add_insecure_port(f"{host}:{self.base_port + self.rank}")
        self._server.start()

    def _stub(self, rank: int):
        rank = int(rank)
        if rank not in self._channels:
            addr = (f"{self.ip_config.get(rank, '127.0.0.1')}:"
                    f"{self.base_port + rank}")
            self._channels[rank] = grpc.insecure_channel(
                addr, options=[("grpc.max_send_message_length", MAX_MSG),
                               ("grpc.max_receive_message_length", MAX_MSG)])
        ch = self._channels[rank]
        return ch.unary_unary(f"/{_SERVICE}/{_METHOD}",
                              request_serializer=lambda b: b,
                              response_deserializer=lambda b: b)

    def send_message(self, msg: Message) -> None:
        blob = msg.encode()
        stub = self._stub(msg.get_receiver_id())
        from ....obs import trace as obs_trace
        from ..backoff import retry_with_backoff
        # one span per send with backoff retries as events (see the TCP
        # manager — identical instrumentation, different transport label)
        with obs_trace.span(
                "comm.send",
                attrs={"transport": "grpc",
                       "receiver": int(msg.get_receiver_id()),
                       "msg_type": str(msg.get_type()),
                       "bytes": len(blob)}) as sp:
            retry_with_backoff(
                lambda: stub(blob, timeout=60.0), retry_on=(grpc.RpcError,),
                describe=f"grpc send {self.rank}->{msg.get_receiver_id()}",
                on_retry=lambda a, d, e: sp.add_event(
                    "retry", attempt=a, delay_s=round(d, 4),
                    error=type(e).__name__),
                **self.retry)

    def handle_receive_message(self) -> None:
        self._running = True
        while self._running:
            try:
                blob = self._q.get(timeout=0.1)
            except queue.Empty:
                continue
            self.notify(Message.decode(blob))

    def stop_receive_message(self) -> None:
        self._running = False
        self._server.stop(grace=0.5)
        for ch in self._channels.values():
            ch.close()
