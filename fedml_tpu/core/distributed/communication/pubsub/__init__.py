"""Pub/sub control-plane transport with bulk-payload offload — the
MQTT+S3 role.

Parity target: the reference's default cross-silo/cross-device transport
(``mqtt_s3/mqtt_s3_multi_clients_comm_manager.py:20``): control messages
ride MQTT topics ``fedml_<runid>_<src>_<dst>``, model payloads are uploaded
to S3 and the message carries the key; the broker's last-will marks dead
clients. paho/MQTT brokers are unavailable in this environment, so the
broker here is a stdlib-TCP pub/sub daemon with the same semantics
(topic subscribe/publish, per-connection last-will) — protocol-shape
parity, not MQTT wire compatibility.

``PubSubStorageCommManager`` implements the control/data split: any
``Message`` whose payload exceeds ``offload_threshold`` bytes has its
``model_params`` field swapped for a ``model_params_url`` object-store key
(:mod:`...distributed_storage`), exactly the reference's S3 pattern.
"""

from __future__ import annotations

import hmac
import hashlib
import logging
import os
import socket
import struct
import threading
from typing import Dict, List, Optional, Tuple

import msgpack

from ..base_com_manager import BaseCommunicationManager
from ..message import Message
from ...distributed_storage import LocalObjectStorage

logger = logging.getLogger(__name__)


def broker_secret() -> Optional[bytes]:
    """Deployment-wide shared secret for broker authentication, from
    ``FEDML_TPU_BROKER_SECRET``. None = open broker (local-first default).
    The reference binds devices through its account manager
    (``scheduler_core/account_manager.py:1-469``); this is the local
    equivalent: no secret, no pub/sub."""
    s = os.environ.get("FEDML_TPU_BROKER_SECRET", "")
    return s.encode() if s else None


def _challenge_mac(secret: bytes, nonce: bytes) -> str:
    return hmac.new(secret, b"fedml-tpu/broker-auth" + nonce,
                    hashlib.sha256).hexdigest()


def client_connect(host: str, port: int,
                   secret: Optional[bytes] = None) -> socket.socket:
    """Connect to a PubSubBroker and complete its hello/challenge
    handshake. The broker always speaks first (a ``hello`` frame); when it
    demands auth the client must answer the nonce with an HMAC under the
    shared secret before any sub/pub/lwt is accepted."""
    sock = socket.create_connection((host, int(port)))
    hello = _recv_frame(sock)
    if not isinstance(hello, dict) or hello.get("kind") != "hello":
        sock.close()
        raise ConnectionError("broker did not send hello frame")
    if hello.get("auth_required"):
        if secret is None:
            secret = broker_secret()
        if secret is None:
            sock.close()
            raise PermissionError(
                "broker requires authentication; set "
                "FEDML_TPU_BROKER_SECRET or pass secret=")
        _send_frame(sock, {"kind": "auth", "mac": _challenge_mac(
            secret, bytes.fromhex(hello["nonce"]))})
        # the broker acks the handshake so a wrong secret surfaces HERE as
        # PermissionError, not later as an unexplained dead connection
        ack = _recv_frame(sock)
        if (not isinstance(ack, dict) or ack.get("kind") != "auth_result"
                or not ack.get("ok")):
            sock.close()
            raise PermissionError(
                "broker rejected authentication (wrong shared secret?)")
    return sock


def _send_frame(sock: socket.socket, obj) -> None:
    blob = msgpack.packb(obj, use_bin_type=True)
    sock.sendall(struct.pack(">I", len(blob)) + blob)


def _recv_frame(sock: socket.socket):
    hdr = b""
    while len(hdr) < 4:
        chunk = sock.recv(4 - len(hdr))
        if not chunk:
            return None
        hdr += chunk
    n, = struct.unpack(">I", hdr)
    buf = b""
    while len(buf) < n:
        chunk = sock.recv(min(n - len(buf), 1 << 16))
        if not chunk:
            return None
        buf += chunk
    return msgpack.unpackb(buf, raw=False)


class PubSubBroker:
    """Topic broker: SUB/PUB/LWT frames over TCP. One per deployment (the
    MQTT broker analogue). With ``secret`` set (default: the
    ``FEDML_TPU_BROKER_SECRET`` env), every connection must answer a fresh
    HMAC challenge before any frame is honored — an unauthenticated peer
    that reaches the socket cannot publish ``start_train`` (or anything
    else)."""

    def __init__(self, host: str = "127.0.0.1", port: int = 0,
                 secret: Optional[bytes] = None):
        self._srv = socket.create_server((host, port))
        self.port = self._srv.getsockname()[1]
        self.host = host
        self.secret = secret if secret is not None else broker_secret()
        self._subs: Dict[str, List[socket.socket]] = {}
        self._wills: Dict[socket.socket, Tuple[str, dict]] = {}
        self._lock = threading.Lock()
        # per-subscriber write locks: concurrent publishes from different
        # connection threads must not interleave frame bytes
        self._send_locks: Dict[socket.socket, threading.Lock] = {}
        self._running = True
        threading.Thread(target=self._accept_loop, daemon=True).start()

    def _handshake(self, conn: socket.socket) -> bool:
        """Broker speaks first: hello (+nonce). With a secret configured,
        the first client frame must be the HMAC answer."""
        nonce = os.urandom(16)
        _send_frame(conn, {"kind": "hello",
                           "auth_required": self.secret is not None,
                           "nonce": nonce.hex()})
        if self.secret is None:
            return True
        frame = _recv_frame(conn)
        ok = (isinstance(frame, dict) and frame.get("kind") == "auth"
              and hmac.compare_digest(
                  str(frame.get("mac", "")),
                  _challenge_mac(self.secret, nonce)))
        try:
            _send_frame(conn, {"kind": "auth_result", "ok": bool(ok)})
        except OSError:
            return False
        if not ok:
            logger.warning("broker: rejecting unauthenticated connection")
        return ok

    def _accept_loop(self) -> None:
        while self._running:
            try:
                conn, _ = self._srv.accept()
            except OSError:
                return
            threading.Thread(target=self._serve, args=(conn,),
                             daemon=True).start()

    def _serve(self, conn: socket.socket) -> None:
        try:
            try:
                if not self._handshake(conn):
                    conn.close()
                    return
            except OSError:
                return
            while True:
                frame = _recv_frame(conn)
                if frame is None:
                    break
                kind = frame.get("kind")
                if kind == "sub":
                    with self._lock:
                        self._subs.setdefault(frame["topic"], []).append(conn)
                        self._send_locks.setdefault(conn, threading.Lock())
                elif kind == "pub":
                    self._publish(frame["topic"], frame["payload"])
                elif kind == "lwt":
                    with self._lock:
                        self._wills[conn] = (frame["topic"],
                                             frame["payload"])
                elif kind == "disconnect":
                    # graceful goodbye clears the will (MQTT semantics:
                    # LWT fires only on abnormal disconnect)
                    with self._lock:
                        self._wills.pop(conn, None)
        finally:
            with self._lock:
                will = self._wills.pop(conn, None)
                for lst in self._subs.values():
                    if conn in lst:
                        lst.remove(conn)
                self._send_locks.pop(conn, None)
            if will is not None:  # last-will: notify liveness watchers
                self._publish(*will)

    def _publish(self, topic: str, payload) -> None:
        with self._lock:
            targets = [(t, self._send_locks.setdefault(t, threading.Lock()))
                       for t in self._subs.get(topic, [])]
        for t, slock in targets:
            try:
                with slock:
                    _send_frame(t, {"topic": topic, "payload": payload})
            except OSError:
                pass

    def stop(self) -> None:
        self._running = False
        try:
            self._srv.close()
        except OSError:
            pass


class PubSubStorageCommManager(BaseCommunicationManager):
    """MQTT+S3-analogue manager: control plane = broker topics
    ``fedml_<run>_<src>_<dst>``; data plane = object store."""

    OFFLOAD_KEYS = (Message.MSG_ARG_KEY_MODEL_PARAMS,)

    def __init__(self, rank: int, broker_host: str = "127.0.0.1",
                 broker_port: int = 0, run_id: str = "0",
                 storage: Optional[LocalObjectStorage] = None,
                 offload_threshold: int = 4096,
                 secret: Optional[bytes] = None):
        super().__init__()
        self.rank = int(rank)
        self.run_id = run_id
        self.storage = storage or LocalObjectStorage()
        self.offload_threshold = int(offload_threshold)
        self._sock = client_connect(broker_host, broker_port, secret)
        self._running = False
        self._lock = threading.Lock()
        # subscribe to every topic addressed to me: fedml_<run>_*_<me>
        _send_frame(self._sock, {"kind": "sub",
                                 "topic": self._topic("*", self.rank)})
        # last-will: liveness signal on the server's status topic (same
        # wire encoding as a normal publish so the receive path is uniform)
        will = Message("client_offline", self.rank, 0)
        _send_frame(self._sock, {"kind": "lwt",
                                 "topic": self._topic("*", 0),
                                 "payload": will.encode()})

    def _topic(self, src, dst) -> str:
        return f"fedml_{self.run_id}_{src}_{dst}"

    def send_message(self, msg: Message) -> None:
        from ..message import _pack_np
        params = dict(msg.msg_params)
        for key in self.OFFLOAD_KEYS:
            if key in params:
                blob = msgpack.packb(params[key], default=_pack_np,
                                     use_bin_type=True)
                if len(blob) >= self.offload_threshold:
                    # control/data split: payload -> object store, message
                    # carries the key (reference S3 write-on-send :274-304)
                    params.pop(key)
                    params[Message.MSG_ARG_KEY_MODEL_PARAMS_URL] = (
                        self.storage.put_object(blob))
        wire = Message()
        wire.msg_params = params
        from ....obs import trace as obs_trace
        # same send-span instrumentation as TCP/gRPC; the traceparent
        # param survives the control/data-plane split (it stays in the
        # control frame, never offloaded)
        with obs_trace.span(
                "comm.send",
                attrs={"transport": "pubsub",
                       "receiver": int(msg.get_receiver_id()),
                       "msg_type": str(msg.get_type())}):
            with self._lock:
                _send_frame(self._sock, {
                    "kind": "pub",
                    "topic": self._topic("*", msg.get_receiver_id()),
                    "payload": wire.encode()})

    def handle_receive_message(self) -> None:
        # blocking reads; stop_receive_message closes the socket which
        # unblocks recv — a read timeout could desync mid-frame instead
        self._running = True
        while self._running:
            try:
                frame = _recv_frame(self._sock)
            except OSError:
                break
            if frame is None:
                break
            msg = Message.decode(bytes(frame["payload"]))
            url = msg.get(Message.MSG_ARG_KEY_MODEL_PARAMS_URL)
            if url:  # data-plane fetch (reference read-on-receive :215-226)
                from ..message import _unpack_np
                blob = self.storage.get_object(url)
                msg.add_params(
                    Message.MSG_ARG_KEY_MODEL_PARAMS,
                    msgpack.unpackb(blob, ext_hook=_unpack_np, raw=False,
                                    strict_map_key=False))
            self.notify(msg)

    def stop_receive_message(self) -> None:
        self._running = False
        try:
            # graceful goodbye clears the last-will at the broker, then an
            # orderly FIN (a bare close() can RST mid-frame and race the
            # broker's reader thread at interpreter shutdown)
            with self._lock:
                _send_frame(self._sock, {"kind": "disconnect"})
            self._sock.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass
        try:
            self._sock.close()
        except OSError:
            pass
