"""Distributed runtime & communication (reference ``core/distributed/``):
Message envelope, transport backends (in-proc / TCP / gRPC), the
FedMLCommManager event-loop base, decentralized topologies, and the
algorithm Flow DAG."""

from .communication.message import Message
from .communication.base_com_manager import BaseCommunicationManager, Observer
from .fedml_comm_manager import FedMLCommManager

__all__ = ["Message", "BaseCommunicationManager", "Observer",
           "FedMLCommManager"]
