"""Executor role for FedMLAlgorithmFlow (reference ``fedml_executor.py:4``):
holds params, exposes ``get/set_params``, and is the ``self`` of flow
callables."""

from __future__ import annotations

from typing import Any, List, Optional


class FedMLExecutor:
    def __init__(self, id: int, neighbor_id_list: Optional[List[int]] = None):
        self.id = int(id)
        self.neighbor_id_list = list(neighbor_id_list or [])
        self._params: Any = None

    def get_params(self) -> Any:
        return self._params

    def set_params(self, params: Any) -> None:
        self._params = params
