"""FedMLAlgorithmFlow — declarative DAG of named flows over executors.

Parity target: reference ``core/distributed/flow/fedml_flow.py:20``
(``add_flow`` :67, ``build`` :78, message-driven step chaining) +
``fedml_executor.py:4``. A *flow* is a named step run by an executor role
(server / client); ``build`` chains them so finishing one flow triggers the
next across the transport. This single-process version runs the chain over
the in-proc broker — same FSM, no cluster.
"""

from .fedml_executor import FedMLExecutor
from .fedml_flow import FedMLAlgorithmFlow

__all__ = ["FedMLExecutor", "FedMLAlgorithmFlow"]
