"""Declarative flow DAG (reference ``fedml_flow.py:20``).

Usage parity with the reference:

    flow = FedMLAlgorithmFlow(args, executor)
    flow.add_flow("init_global_model", server.init_global_model)
    flow.add_flow("local_training", client.local_training, loop=True)
    flow.add_flow("aggregate", server.aggregate)
    flow.build()
    flow.run()

Each flow step runs on the executors whose role matches the bound method's
owner; step completion posts a FLOW_FINISH message that triggers the next
step for every participant, giving the same message-driven chaining as the
reference without requiring its per-flow manager subclasses.
"""

from __future__ import annotations

import logging
import threading
from typing import Any, Callable, Dict, List, Optional

from ..communication.inproc import InProcBroker
from ..communication.message import Message

logger = logging.getLogger(__name__)

MSG_TYPE_FLOW_FINISH = "flow_finish"
MSG_TYPE_FLOW_PARAMS = "flow_params"


class _FlowStep:
    def __init__(self, name: str, executor, method: Callable, loop: bool):
        self.name = name
        self.executor = executor
        self.method = method
        self.loop = loop


class FedMLAlgorithmFlow:
    """Single-controller flow engine: steps execute in order; ``loop=True``
    marks the loop body boundary (reference flows repeat
    [loop-start .. next non-loop flow) ``comm_round`` times)."""

    def __init__(self, args, executor=None):
        self.args = args
        self.flows: List[_FlowStep] = []
        self.broker = InProcBroker()
        self._built = False

    def add_flow(self, name: str, method: Callable, loop: bool = False
                 ) -> "FedMLAlgorithmFlow":
        executor = getattr(method, "__self__", None)
        self.flows.append(_FlowStep(name, executor, method, loop))
        return self

    def build(self) -> None:
        if not self.flows:
            raise ValueError("no flows added")
        self._built = True
        logger.info("flow DAG: %s", " -> ".join(
            f.name + ("*" if f.loop else "") for f in self.flows))

    def run(self) -> Any:
        """Execute the chain. Values returned by a step are handed to the
        next step if its signature accepts an argument (Params-passing of
        the reference)."""
        if not self._built:
            raise RuntimeError("call build() before run()")
        rounds = int(getattr(self.args, "comm_round", 1))
        # identify the loop body [first loop flow .. last loop flow]
        loop_idx = [i for i, f in enumerate(self.flows) if f.loop]
        value: Any = None
        i = 0
        loops_done = 0
        while i < len(self.flows):
            step = self.flows[i]
            value = self._run_step(step, value)
            if loop_idx and i == loop_idx[-1] and loops_done < rounds - 1:
                loops_done += 1
                i = loop_idx[0]
                continue
            i += 1
        return value

    def _run_step(self, step: _FlowStep, value: Any) -> Any:
        logger.info("flow step: %s", step.name)
        # Decide by signature whether the step accepts the chained value —
        # catching TypeError instead would swallow genuine TypeErrors raised
        # inside the step body and double-execute its side effects.
        if value is None:
            return step.method()
        import inspect
        try:
            sig = inspect.signature(step.method)
            accepts_arg = any(
                p.kind in (p.POSITIONAL_ONLY, p.POSITIONAL_OR_KEYWORD,
                           p.VAR_POSITIONAL)
                for p in sig.parameters.values())
        except (TypeError, ValueError):  # builtins without signatures
            accepts_arg = True
        return step.method(value) if accepts_arg else step.method()
