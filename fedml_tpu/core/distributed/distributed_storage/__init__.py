"""Bulk-payload object store — the data plane of the control/data split.

Parity target: the reference's S3 remote storage
(``communication/s3/remote_storage.py:28`` — ``write_model`` :75,
``read_model`` :215) and the decentralized variants
(``core/distributed/distributed_storage/`` web3.storage / Theta EdgeStore):
model payloads leave the control channel; messages carry only a key/URL.

Local-first implementation: a content-addressed store on a shared
filesystem path (``put`` returns ``cas://<sha256>``); the interface is the
narrow waist (``put_object``/``get_object``/``write_model``/``read_model``)
so an S3/GCS/web3 client can be dropped in behind it unchanged.
"""

from __future__ import annotations

import hashlib
import os
from typing import Any, Optional

from ..communication.message import dumps_tree, loads_tree


class LocalObjectStorage:
    """Content-addressed blob store rooted at ``root`` (defaults to the
    cache dir; cross-silo tests share one root the way silos share S3)."""

    SCHEME = "cas://"

    def __init__(self, root: Optional[str] = None):
        self.root = os.path.expanduser(
            root or os.environ.get("FEDML_TPU_STORAGE_DIR",
                                   "~/.cache/fedml_tpu/storage"))
        os.makedirs(self.root, exist_ok=True)

    def _path(self, digest: str) -> str:
        return os.path.join(self.root, digest)

    def put_object(self, blob: bytes) -> str:
        digest = hashlib.sha256(blob).hexdigest()
        path = self._path(digest)
        if not os.path.exists(path):
            tmp = path + ".tmp"
            with open(tmp, "wb") as f:
                f.write(blob)
            os.replace(tmp, path)
        return self.SCHEME + digest

    def get_object(self, key: str) -> bytes:
        digest = key.removeprefix(self.SCHEME)
        with open(self._path(digest), "rb") as f:
            blob = f.read()
        if hashlib.sha256(blob).hexdigest() != digest:
            raise IOError(f"object store corruption for {key}")
        return blob

    # --- model payload convenience (reference write_model/read_model) ------
    # wire tree codec, NOT pickle: stored payloads can come from remote
    # silos, and reading one must never execute code.
    def write_model(self, params: Any) -> str:
        return self.put_object(dumps_tree(params))

    def read_model(self, key: str) -> Any:
        return loads_tree(self.get_object(key))
