"""Round-level checkpoint/resume via orbax.

The reference has NO FL-round checkpointing (SURVEY §5.4: the ``comm_round``
loop keeps state in memory only, ``sp/fedavg/fedavg_api.py:72``; only the
LLM path saves HF checkpoints). Here it is default-capable and cheap: the
full FL state is (params, server_state, client_states, host RNG key, DP
accountant, and — when a stateful defense runs the default sharded path —
the feature-sharded cross-round defense state, e.g. the foolsgold
similarity history, so crash-resume replays identical defense verdicts;
with ``sharded_defense: false`` the host kernels' state is NOT
checkpointed and the engine warns that resume restarts it cold), a few MB
for
classic models — saved every ``checkpoint_every_rounds`` and restored on
construction, which also gives the elastic-recovery story the reference
lacks (round-level restart after failure).
"""

from __future__ import annotations

import logging
import os
from typing import Any, Dict, Optional, Tuple

import jax
import numpy as np

logger = logging.getLogger(__name__)

PyTree = Any


class RoundCheckpointer:
    """Thin orbax wrapper keyed by round index. Disabled when ``directory``
    is falsy (the default)."""

    def __init__(self, directory: Optional[str], every_rounds: int = 0,
                 max_to_keep: int = 3):
        self.enabled = bool(directory) and every_rounds > 0
        self.every = max(int(every_rounds), 1)
        self._mgr = None
        if self.enabled:
            import orbax.checkpoint as ocp
            path = os.path.abspath(os.path.expanduser(directory))
            os.makedirs(path, exist_ok=True)
            self._mgr = ocp.CheckpointManager(
                path, options=ocp.CheckpointManagerOptions(
                    max_to_keep=max_to_keep, create=True))

    def maybe_save(self, round_idx: int, state: PyTree) -> bool:
        """Save if the cadence hits. State leaves must be arrays.

        The save is ASYNC: only the host snapshot below is synchronous;
        the disk write proceeds in orbax's background thread while the
        round loop keeps training (the old per-save ``wait_until_finished``
        stalled every checkpoint round for the full write). Waiting happens
        in :meth:`flush`/:meth:`close` and before :meth:`latest` restores.

        The eager ``device_get`` + ``np.asarray`` copy is load-bearing for
        ``donate_buffers``: it snapshots the state to HOST MEMORY *before*
        the next round program donates (and XLA overwrites) the very
        buffers being saved — an async writer holding device references
        instead would read donated garbage."""
        if not self.enabled:
            return False
        if (round_idx + 1) % self.every != 0:
            return False
        import orbax.checkpoint as ocp
        state = jax.tree_util.tree_map(np.asarray, jax.device_get(state))
        self._mgr.save(round_idx, args=ocp.args.StandardSave(state))
        logger.info("checkpointing round %d (async)", round_idx)
        return True

    def flush(self) -> None:
        """Block until every scheduled save is durable on disk. The
        blocking wall time lands in the ``fed_checkpoint_flush_seconds``
        histogram — it is the checkpointing cost the round loop actually
        pays (the writes themselves overlap training)."""
        if self._mgr is not None:
            import time

            from .obs import metrics as obs_metrics
            t0 = time.perf_counter()
            self._mgr.wait_until_finished()
            obs_metrics.record_checkpoint_flush(time.perf_counter() - t0)

    def latest(self, template: PyTree) -> Optional[Tuple[int, PyTree]]:
        """Restore the newest checkpoint (matching ``template``'s structure)
        or None. Any save still in flight on THIS manager is awaited first
        so a restore never reads a half-committed step."""
        if not self.enabled:
            return None
        self.flush()
        step = self._mgr.latest_step()
        if step is None:
            return None
        import orbax.checkpoint as ocp
        template = jax.tree_util.tree_map(np.asarray,
                                          jax.device_get(template))
        restored = self._mgr.restore(
            step, args=ocp.args.StandardRestore(template))
        return int(step), restored

    def close(self) -> None:
        if self._mgr is not None:
            self._mgr.wait_until_finished()
            self._mgr.close()
