"""Params / Context — the kwargs bag and global blackboard of the algorithm
frame (reference ``core/alg_frame/params.py:1``, ``context.py:19``). Used by
trust/privacy hooks to share round state without threading it through every
signature."""

from __future__ import annotations

from typing import Any, Dict, Iterator


class Params:
    """An attribute/key hybrid bag (reference ``Params``)."""

    def __init__(self, **kwargs: Any):
        self.__dict__.update(kwargs)

    def add(self, name: str, value: Any) -> "Params":
        self.__dict__[name] = value
        return self

    def get(self, name: str, default: Any = None) -> Any:
        return self.__dict__.get(name, default)

    def __contains__(self, name: str) -> bool:
        return name in self.__dict__

    def __getitem__(self, name: str) -> Any:
        return self.__dict__[name]

    def __setitem__(self, name: str, value: Any) -> None:
        self.__dict__[name] = value

    def keys(self) -> Iterator[str]:
        return iter(self.__dict__)

    def to_dict(self) -> Dict[str, Any]:
        return dict(self.__dict__)


class Context(Params):
    """Process-wide singleton blackboard (reference ``context.py:19``)."""

    _instance: "Context" = None

    def __new__(cls, *a, **kw):
        if cls._instance is None:
            cls._instance = super().__new__(cls)
        return cls._instance

    @classmethod
    def reset(cls) -> None:
        cls._instance = None
