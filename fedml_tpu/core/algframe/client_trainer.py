"""Client-side trainer specs as pure functions.

Parity target: ``ClientTrainer`` ABC (reference
``core/alg_frame/client_trainer.py:10`` — ``get/set_model_params``, ``train``,
``test``) and the default concrete trainers
(``ml/trainer/my_model_trainer_classification.py:14`` train loop :21-77).

A trainer here is a *spec*: ``loss(params, batch, rng) -> (loss, aux)`` and
``eval_stats(params, batch) -> dict of sums``. The local SGD loop itself lives
in ``local_training.py`` and is shared by every federated optimizer; get/set
of model params is replaced by pytrees flowing through function arguments.
The reference's before/after-training attack/DP hooks
(``client_trainer.py:61,80``) map to the engine-level defense -> aggregate ->
DP pipeline in ``simulation/tpu/engine.py`` (built from ``core/security`` and
``core/dp``).
"""

from __future__ import annotations

from typing import Any, Callable, Dict, Tuple

import jax
import jax.numpy as jnp
import optax

PyTree = Any
Batch = Dict[str, jnp.ndarray]  # {"x", "y", "mask"}


class TrainerSpec:
    """Pure-function trainer: subclass or compose to customize the loss.

    ``apply_fn(params, x, rng=...)`` is the model forward (flax ``apply``).
    """

    def __init__(self, apply_fn: Callable[..., jnp.ndarray]):
        self.apply_fn = apply_fn

    def loss(self, params: PyTree, batch: Batch, rng: jax.Array
             ) -> Tuple[jnp.ndarray, Dict[str, jnp.ndarray]]:
        raise NotImplementedError

    def eval_stats(self, params: PyTree, batch: Batch) -> Dict[str, jnp.ndarray]:
        raise NotImplementedError


class ClassificationTrainer(TrainerSpec):
    """Cross-entropy classification (``ModelTrainerCLS``,
    ``my_model_trainer_classification.py:14``). Masked mean over real samples
    so padded slots contribute nothing."""

    def loss(self, params, batch, rng):
        logits = self.apply_fn(params, batch["x"], rng=rng, train=True)
        labels = batch["y"].astype(jnp.int32)
        per_ex = optax.softmax_cross_entropy_with_integer_labels(logits, labels)
        mask = batch["mask"].astype(per_ex.dtype)
        denom = jnp.maximum(jnp.sum(mask), 1.0)
        loss = jnp.sum(per_ex * mask) / denom
        correct = jnp.sum((jnp.argmax(logits, -1) == labels) * mask)
        return loss, {"loss_sum": jnp.sum(per_ex * mask),
                      "correct": correct, "count": jnp.sum(mask)}

    def eval_stats(self, params, batch):
        logits = self.apply_fn(params, batch["x"], train=False)
        labels = batch["y"].astype(jnp.int32)
        per_ex = optax.softmax_cross_entropy_with_integer_labels(logits, labels)
        mask = batch["mask"].astype(per_ex.dtype)
        correct = jnp.sum((jnp.argmax(logits, -1) == labels) * mask)
        return {"loss_sum": jnp.sum(per_ex * mask), "correct": correct,
                "count": jnp.sum(mask)}


class SequenceTrainer(TrainerSpec):
    """Per-token cross-entropy for next-word-prediction tasks (reference
    ``my_model_trainer_nwp.py``): labels [bs, L], logits [bs, L, V]; the
    per-sample mask broadcasts over tokens."""

    def loss(self, params, batch, rng):
        logits = self.apply_fn(params, batch["x"], rng=rng, train=True)
        labels = batch["y"].astype(jnp.int32)
        per_tok = optax.softmax_cross_entropy_with_integer_labels(logits, labels)
        mask = batch["mask"].astype(per_tok.dtype)[:, None]  # [bs,1] over [bs,L]
        tok_count = jnp.sum(mask * jnp.ones_like(per_tok))
        denom = jnp.maximum(tok_count, 1.0)
        loss = jnp.sum(per_tok * mask) / denom
        correct = jnp.sum((jnp.argmax(logits, -1) == labels) * mask)
        return loss, {"loss_sum": jnp.sum(per_tok * mask),
                      "correct": correct, "count": tok_count}

    def eval_stats(self, params, batch):
        logits = self.apply_fn(params, batch["x"], train=False)
        labels = batch["y"].astype(jnp.int32)
        per_tok = optax.softmax_cross_entropy_with_integer_labels(logits, labels)
        mask = batch["mask"].astype(per_tok.dtype)[:, None]
        tok_count = jnp.sum(mask * jnp.ones_like(per_tok))
        correct = jnp.sum((jnp.argmax(logits, -1) == labels) * mask)
        return {"loss_sum": jnp.sum(per_tok * mask), "correct": correct,
                "count": tok_count}


class MultiLabelTrainer(TrainerSpec):
    """Sigmoid-BCE tag prediction (reference
    ``my_model_trainer_tag_prediction.py`` — stackoverflow_lr). ``y`` is a
    multi-hot [bs, n_tags] matrix; accuracy is exact-match-free micro-F1-ish:
    we report per-tag correctness so curves stay informative."""

    def loss(self, params, batch, rng):
        logits = self.apply_fn(params, batch["x"], rng=rng, train=True)
        labels = batch["y"].astype(logits.dtype)
        per_tag = optax.sigmoid_binary_cross_entropy(logits, labels)
        per_ex = jnp.mean(per_tag, axis=-1)
        mask = batch["mask"].astype(per_ex.dtype)
        denom = jnp.maximum(jnp.sum(mask), 1.0)
        loss = jnp.sum(per_ex * mask) / denom
        pred = (logits > 0).astype(labels.dtype)
        correct = jnp.sum(jnp.mean((pred == labels).astype(jnp.float32), -1)
                          * mask)
        return loss, {"loss_sum": jnp.sum(per_ex * mask),
                      "correct": correct, "count": jnp.sum(mask)}

    def eval_stats(self, params, batch):
        logits = self.apply_fn(params, batch["x"], train=False)
        labels = batch["y"].astype(logits.dtype)
        per_tag = optax.sigmoid_binary_cross_entropy(logits, labels)
        per_ex = jnp.mean(per_tag, axis=-1)
        mask = batch["mask"].astype(per_ex.dtype)
        pred = (logits > 0).astype(labels.dtype)
        correct = jnp.sum(jnp.mean((pred == labels).astype(jnp.float32), -1)
                          * mask)
        return {"loss_sum": jnp.sum(per_ex * mask), "correct": correct,
                "count": jnp.sum(mask)}


class RegressionTrainer(TrainerSpec):
    """MSE regression (covers the reference's tag-prediction style trainers,
    ``my_model_trainer_tag_prediction.py``)."""

    def loss(self, params, batch, rng):
        preds = self.apply_fn(params, batch["x"], rng=rng, train=True)
        labels = batch["y"].astype(preds.dtype)
        if preds.ndim > labels.ndim:
            labels = labels[..., None]
        per_ex = jnp.mean((preds - labels) ** 2, axis=-1)
        mask = batch["mask"].astype(per_ex.dtype)
        denom = jnp.maximum(jnp.sum(mask), 1.0)
        loss = jnp.sum(per_ex * mask) / denom
        return loss, {"loss_sum": jnp.sum(per_ex * mask),
                      "correct": jnp.zeros(()), "count": jnp.sum(mask)}

    def eval_stats(self, params, batch):
        preds = self.apply_fn(params, batch["x"], train=False)
        labels = batch["y"].astype(preds.dtype)
        if preds.ndim > labels.ndim:
            labels = labels[..., None]
        per_ex = jnp.mean((preds - labels) ** 2, axis=-1)
        mask = batch["mask"].astype(per_ex.dtype)
        return {"loss_sum": jnp.sum(per_ex * mask),
                "correct": jnp.zeros(()), "count": jnp.sum(mask)}


def make_trainer_spec(fed, bundle) -> TrainerSpec:
    """Pick the TrainerSpec from the dataset's declared task (reference
    ``ml/trainer/trainer_creator.py`` chooses per-dataset trainers)."""
    task = getattr(fed, "task", "classification")
    if task == "classification" and fed.train.y.ndim >= 4:
        # caller built the dataset without declaring a task: a trailing axis
        # on y means per-token ints (sequence) or multi-hot floats
        import jax.numpy as _jnp
        task = ("multilabel" if _jnp.issubdtype(fed.train.y.dtype,
                                                _jnp.floating)
                else "sequence")
    if task in ("llm", "causal_lm"):
        from ...llm.trainer import CausalLMTrainer
        return CausalLMTrainer(bundle.apply)
    if task == "sequence":
        return SequenceTrainer(bundle.apply)
    if task == "multilabel":
        return MultiLabelTrainer(bundle.apply)
    if task == "regression":
        return RegressionTrainer(bundle.apply)
    return ClassificationTrainer(bundle.apply)


def make_inner_optimizer(name: str, learning_rate, momentum: float = 0.0,
                         weight_decay: float = 0.0) -> optax.GradientTransformation:
    """The client's inner optimizer (reference: torch SGD/Adam built in the
    trainer, ``my_model_trainer_classification.py:21-40``)."""
    name = (name or "sgd").lower()
    if name == "adamw":
        # adamw handles decoupled decay itself — do not also add_decayed_weights
        return optax.adamw(learning_rate, weight_decay=weight_decay)
    txs = []
    if weight_decay:
        txs.append(optax.add_decayed_weights(weight_decay))
    if name == "sgd":
        txs.append(optax.sgd(learning_rate, momentum=momentum or None))
    elif name == "adam":
        txs.append(optax.adam(learning_rate))
    else:
        raise ValueError(f"unknown client_optimizer {name!r}")
    return optax.chain(*txs)
