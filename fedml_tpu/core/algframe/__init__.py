from .types import ClientData, ClientOutput, TrainHyper
from .client_trainer import (TrainerSpec, ClassificationTrainer,
                             RegressionTrainer, make_inner_optimizer)
from .local_training import run_local_sgd, evaluate
from .params import Params, Context

__all__ = ["ClientData", "ClientOutput", "TrainHyper", "TrainerSpec",
           "ClassificationTrainer", "RegressionTrainer",
           "make_inner_optimizer", "run_local_sgd", "evaluate",
           "Params", "Context"]
