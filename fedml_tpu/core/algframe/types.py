"""Core pytree datatypes of the algorithm frame.

The reference passes model state-dicts + ``(num_samples, params)`` tuples
between ``ClientTrainer`` and ``ServerAggregator``
(``core/alg_frame/client_trainer.py``, ``server_aggregator.py``,
``ml/aggregator/agg_operator.py:8-30``). Here the equivalents are typed
pytrees so an entire round can flow through ``jit``/``shard_map``.
"""

from __future__ import annotations

from typing import Any, Dict

import jax.numpy as jnp
from flax import struct

PyTree = Any


@struct.dataclass
class ClientData:
    """One client's local dataset, padded to a static shape.

    ``x``: [n_batches, batch_size, ...features]
    ``y``: [n_batches, batch_size] (int labels) or [..., dim] for regression
    ``mask``: [n_batches, batch_size] — 1.0 for real samples, 0.0 for padding
    ``num_samples``: scalar float — the aggregation weight ``n_k``
    (reference ``fedavg_api.py:144``: weights are post-sampling ``n_k/Σn``).

    Padding+masking is how ragged per-client datasets become jit-compatible
    (SURVEY §7 "hard parts": per-client data heterogeneity inside jit).
    """
    x: jnp.ndarray
    y: jnp.ndarray
    mask: jnp.ndarray
    num_samples: jnp.ndarray


@struct.dataclass
class ClientOutput:
    """What one simulated client returns from local training.

    ``update``: pytree delta (local_params − global_params). Delta form makes
    FedOpt/SCAFFOLD/FedNova server transforms uniform and keeps secure
    aggregation / DP noise addition linear.
    ``weight``: scalar aggregation weight (``n_k``).
    ``client_state``: persistent per-client optimizer state (SCAFFOLD control
    variate ``c_i``, FedDyn ``h_i`` — empty dict for stateless optimizers).
    ``extras``: optimizer-specific auxiliary reductions that must ride the
    same psum (e.g. SCAFFOLD's Δc, FedNova's normalization coefficients).
    ``metrics``: scalar training metrics (summed/averaged by the engine).
    """
    update: PyTree
    weight: jnp.ndarray
    client_state: PyTree
    extras: Dict[str, Any]
    metrics: Dict[str, jnp.ndarray]


@struct.dataclass
class TrainHyper:
    """Static-ish per-round hyperparameters threaded into local training.

    ``work_scale`` is the chaos subsystem's straggler knob as *data*: the
    fraction of this client's local steps actually run (1.0 = healthy,
    0.0 = dropped). It is a traced leaf, so per-slot straggler schedules
    flow through the jitted round programs without recompiling — the
    local loop is already a dynamic-trip ``while_loop``."""
    learning_rate: jnp.ndarray
    epochs: int = struct.field(pytree_node=False, default=1)
    round_idx: jnp.ndarray = struct.field(default_factory=lambda: jnp.int32(0))
    work_scale: jnp.ndarray = struct.field(
        default_factory=lambda: jnp.float32(1.0))
