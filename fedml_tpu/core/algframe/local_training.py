"""The shared local-SGD loop — the hot loop of FL simulation.

Parity target: the epochs×batches training loop of
``ml/trainer/my_model_trainer_classification.py:21-77``. TPU-first design:
the loop is a single ``lax.scan`` over ``epochs * n_batches`` steps so XLA
compiles one fused program per round; per-epoch batch-order shuffling is done
with a folded PRNG permutation instead of a stateful DataLoader; padded
batches (clients with fewer samples than the static maximum) are no-ops via
masking, which is what makes ragged client data jit-compatible.

Every federated optimizer reuses this loop and customizes it through a
``grad_transform`` hook (FedProx's proximal term, SCAFFOLD's control-variate
correction, Mime's server-stats step).
"""

from __future__ import annotations

from typing import Any, Callable, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import optax

from .types import ClientData, TrainHyper
from .client_trainer import TrainerSpec

PyTree = Any
GradTransform = Callable[[PyTree, PyTree, Dict[str, Any]], PyTree]


def run_local_sgd(
    spec: TrainerSpec,
    inner_opt: optax.GradientTransformation,
    params: PyTree,
    cdata: ClientData,
    rng: jax.Array,
    hyper: TrainHyper,
    grad_transform: Optional[GradTransform] = None,
    ctx: Optional[Dict[str, Any]] = None,
    init_opt_state: Optional[PyTree] = None,
) -> Tuple[PyTree, PyTree, Dict[str, jnp.ndarray]]:
    """Run ``hyper.epochs`` of SGD over one client's padded batches.

    Returns ``(params, final_opt_state, metrics)`` where metrics are summed
    counts (loss_sum / correct / count) over all real samples seen.
    """
    opt_state = inner_opt.init(params) if init_opt_state is None else init_opt_state
    n_batches = cdata.x.shape[0]
    total_steps = hyper.epochs * n_batches
    data_rng, loop_rng = jax.random.split(rng)
    ctx = ctx or {}

    def step(carry, t):
        params, opt_state, rng = carry
        rng, step_rng = jax.random.split(rng)
        epoch = t // n_batches
        pos = t % n_batches
        perm = jax.random.permutation(jax.random.fold_in(data_rng, epoch), n_batches)
        idx = perm[pos]
        batch = {"x": cdata.x[idx], "y": cdata.y[idx], "mask": cdata.mask[idx]}
        (loss, aux), grads = jax.value_and_grad(spec.loss, has_aux=True)(
            params, batch, step_rng)
        if grad_transform is not None:
            grads = grad_transform(grads, params, ctx)
        updates, new_opt_state = inner_opt.update(grads, opt_state, params)
        new_params = optax.apply_updates(params, updates)
        # All-padding batches must be exact no-ops (momentum would otherwise
        # keep integrating); gate the whole step on batch realness.
        is_real = jnp.sum(batch["mask"]) > 0
        params = jax.tree_util.tree_map(
            lambda new, old: jnp.where(is_real, new, old), new_params, params)
        opt_state = jax.tree_util.tree_map(
            lambda new, old: jnp.where(is_real, new, old), new_opt_state, opt_state)
        return (params, opt_state, rng), aux

    (params, opt_state, _), auxs = jax.lax.scan(
        step, (params, opt_state, loop_rng), jnp.arange(total_steps))
    metrics = {
        "loss_sum": jnp.sum(auxs["loss_sum"]),
        "correct": jnp.sum(auxs["correct"]),
        "count": jnp.sum(auxs["count"]),
    }
    return params, opt_state, metrics


def evaluate(
    spec: TrainerSpec,
    params: PyTree,
    x: jnp.ndarray,
    y: jnp.ndarray,
    mask: jnp.ndarray,
) -> Dict[str, jnp.ndarray]:
    """Batched evaluation over a [n_batches, bs, ...] dataset; returns summed
    stats (caller divides by count). Counterpart of the reference's
    ``_local_test_on_all_clients`` / trainer ``test`` methods."""

    def body(carry, batch):
        stats = spec.eval_stats(params, batch)
        return carry, stats

    _, stats = jax.lax.scan(body, None, {"x": x, "y": y, "mask": mask})
    return {k: jnp.sum(v) for k, v in stats.items()}
