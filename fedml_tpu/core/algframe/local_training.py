"""The shared local-SGD loop — the hot loop of FL simulation.

Parity target: the epochs×batches training loop of
``ml/trainer/my_model_trainer_classification.py:21-77``. TPU-first design:
the loop is a single ``lax.scan`` over ``epochs * n_batches`` steps so XLA
compiles one fused program per round; per-epoch batch-order shuffling is done
with a folded PRNG permutation instead of a stateful DataLoader; padded
batches (clients with fewer samples than the static maximum) are no-ops via
masking, which is what makes ragged client data jit-compatible.

Every federated optimizer reuses this loop and customizes it through a
``grad_transform`` hook (FedProx's proximal term, SCAFFOLD's control-variate
correction, Mime's server-stats step).
"""

from __future__ import annotations

from typing import Any, Callable, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import optax

from .types import ClientData, TrainHyper
from .client_trainer import TrainerSpec

PyTree = Any
GradTransform = Callable[[PyTree, PyTree, Dict[str, Any]], PyTree]


def run_local_sgd(
    spec: TrainerSpec,
    inner_opt: optax.GradientTransformation,
    params: PyTree,
    cdata: ClientData,
    rng: jax.Array,
    hyper: TrainHyper,
    grad_transform: Optional[GradTransform] = None,
    ctx: Optional[Dict[str, Any]] = None,
    init_opt_state: Optional[PyTree] = None,
) -> Tuple[PyTree, PyTree, Dict[str, jnp.ndarray]]:
    """Run ``hyper.epochs`` of SGD over one client's padded batches.

    Returns ``(params, final_opt_state, metrics)`` where metrics are summed
    counts (loss_sum / correct / count) over all real samples seen.

    Ragged clients: the stacked client tensors pad every client to the
    LARGEST client's batch count, so a fixed-trip ``lax.scan`` would burn a
    full fwd+bwd on every padded batch (on hetero Dirichlet partitions that
    is ~2x the real work — measured 5.6s -> 2.8s per 64-client ResNet-56
    round when skipped). Instead the loop is a ``lax.while_loop`` over the
    *dynamic* real-step count — reverse-mode AD never differentiates through
    the loop (grads are taken per step inside), so ``while_loop`` is legal,
    and under ``jax.vmap`` (the engine's client-batched mode) it becomes a
    lanes-masked batched while that exits when the longest client finishes.

    Per-epoch shuffling with a dynamic batch count uses the sort trick: draw
    a uniform key per padded slot, push padded batches to the end with +2.0,
    and argsort — the first ``real_batches`` positions are then a uniform
    permutation of exactly the real batches.
    """
    opt_state = inner_opt.init(params) if init_opt_state is None else init_opt_state
    n_batches = cdata.x.shape[0]
    # [n_batches] — a batch is real iff it has at least one unmasked sample
    batch_real = jnp.any(cdata.mask > 0, axis=tuple(range(1, cdata.mask.ndim)))
    real_batches = jnp.sum(batch_real.astype(jnp.int32))
    # chaos straggler slowdown as data: work_scale < 1 truncates the
    # dynamic step count (ceil keeps at least one step for any scale > 0).
    # At the default work_scale == 1.0 the product and ceil are exact, so
    # the step count — and therefore the trajectory — is bit-identical to
    # the unscaled loop.
    total_steps = jnp.ceil(
        (hyper.epochs * real_batches).astype(jnp.float32)
        * hyper.work_scale).astype(jnp.int32)
    denom = jnp.maximum(real_batches, 1)
    data_rng, loop_rng = jax.random.split(rng)
    ctx = ctx or {}
    zero_metrics = {"loss_sum": jnp.float32(0), "correct": jnp.float32(0),
                    "count": jnp.float32(0)}

    def epoch_order(epoch):
        keys = jax.random.uniform(jax.random.fold_in(data_rng, epoch),
                                  (n_batches,))
        return jnp.argsort(jnp.where(batch_real, keys, keys + 2.0))

    def cond(carry):
        return carry[0] < total_steps

    def body(carry):
        t, params, opt_state, rng, metrics = carry
        rng, step_rng = jax.random.split(rng)
        idx = epoch_order(t // denom)[t % denom]
        batch = {"x": cdata.x[idx], "y": cdata.y[idx], "mask": cdata.mask[idx]}
        (loss, aux), grads = jax.value_and_grad(spec.loss, has_aux=True)(
            params, batch, step_rng)
        if grad_transform is not None:
            grads = grad_transform(grads, params, ctx)
        updates, opt_state = inner_opt.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        metrics = {k: metrics[k] + aux[k].astype(jnp.float32)
                   for k in zero_metrics}
        return (t + 1, params, opt_state, rng, metrics)

    (_, params, opt_state, _, metrics) = jax.lax.while_loop(
        cond, body, (jnp.int32(0), params, opt_state, loop_rng, zero_metrics))
    return params, opt_state, metrics


def effective_steps(cdata: ClientData, epochs: int,
                    work_scale=1.0) -> jnp.ndarray:
    """Number of *real* (non-padding) local SGD steps a client runs: padded
    all-zero-mask batches are gated to no-ops in :func:`run_local_sgd`, so
    K = ceil(epochs x real batches x work_scale). SCAFFOLD / FedNova
    normalizations need this exact count — a chaos straggler that ran half
    its steps must be normalized by the steps it RAN, or its control
    variate / a_i coefficient silently mis-scales."""
    real_batches = jnp.sum(jnp.any(cdata.mask > 0, axis=1).astype(jnp.float32))
    return jnp.maximum(jnp.ceil(epochs * real_batches * work_scale), 1.0)


def full_batch_grad_sum(
    spec: TrainerSpec,
    params: PyTree,
    cdata: ClientData,
    rng: jax.Array,
) -> Tuple[PyTree, Dict[str, jnp.ndarray]]:
    """Masked SUM of per-sample gradients of the loss at ``params`` (the
    un-normalized numerator of :func:`full_batch_grad`): per-batch mean
    gradients re-weighted by real-sample count and summed. This is the
    quantity that is exactly additive across clients, which is what lets
    the engine's client-slot batch folding replace S per-client passes
    with one S-times-wider pass (ISSUE 16)."""

    def body(carry, inp):
        i, batch = inp
        acc_g, acc_m = carry
        grads, aux = jax.grad(spec.loss, has_aux=True)(
            params, batch, jax.random.fold_in(rng, i))
        n = aux["count"]
        acc_g = jax.tree_util.tree_map(
            lambda a, g: a + g * n.astype(g.dtype), acc_g, grads)
        acc_m = jax.tree_util.tree_map(
            lambda a, m: a + m.astype(a.dtype), acc_m, aux)
        return (acc_g, acc_m), None

    zero_g = jax.tree_util.tree_map(jnp.zeros_like, params)
    zero_m = jax.eval_shape(
        lambda: spec.loss(params, jax.tree_util.tree_map(
            lambda a: a[0], {"x": cdata.x, "y": cdata.y, "mask": cdata.mask}),
            rng))[1]
    zero_m = jax.tree_util.tree_map(
        lambda s: jnp.zeros(s.shape, s.dtype), zero_m)
    (acc_g, metrics), _ = jax.lax.scan(
        body, (zero_g, zero_m),
        (jnp.arange(cdata.x.shape[0]),
         {"x": cdata.x, "y": cdata.y, "mask": cdata.mask}))
    return acc_g, metrics


def full_batch_grad(
    spec: TrainerSpec,
    params: PyTree,
    cdata: ClientData,
    rng: jax.Array,
) -> Tuple[PyTree, Dict[str, jnp.ndarray]]:
    """Masked full-dataset gradient of the loss at ``params`` — the per-batch
    mean gradients are re-weighted by real-sample count so the result equals
    the gradient of the mean loss over all real samples. Used by FedSGD and
    Mime's server-statistics update."""
    acc_g, metrics = full_batch_grad_sum(spec, params, cdata, rng)
    denom = jnp.maximum(metrics["count"], 1.0)
    grads = jax.tree_util.tree_map(
        lambda g: g / denom.astype(g.dtype), acc_g)
    return grads, metrics


def evaluate(
    spec: TrainerSpec,
    params: PyTree,
    x: jnp.ndarray,
    y: jnp.ndarray,
    mask: jnp.ndarray,
) -> Dict[str, jnp.ndarray]:
    """Batched evaluation over a [n_batches, bs, ...] dataset; returns summed
    stats (caller divides by count). Counterpart of the reference's
    ``_local_test_on_all_clients`` / trainer ``test`` methods."""

    def body(carry, batch):
        stats = spec.eval_stats(params, batch)
        return carry, stats

    _, stats = jax.lax.scan(body, None, {"x": x, "y": y, "mask": mask})
    return {k: jnp.sum(v) for k, v in stats.items()}
