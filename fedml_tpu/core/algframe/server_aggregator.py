"""User-pluggable server aggregator for the simulator path.

Parity target: reference ``core/alg_frame/server_aggregator.py:14`` (ABC
with ``on_before_aggregation`` :44 / ``aggregate`` :75 /
``on_after_aggregation`` :90 hooks, honored by every runner). TPU-native
shape: the hooks operate on the round's stacked update **matrix** [K, D]
plus weights [K] — exactly what the engine's collect mode emits — and
return the aggregate vector [D]. Passing an instance to ``FedMLRunner``
switches the mesh engine into collect mode automatically.

When a defense is also enabled the defense takes precedence (the reference
runs defenses inside these same hooks; here they are one fused kernel), and
the user aggregator is skipped with a warning.
"""

from __future__ import annotations

from abc import ABC, abstractmethod
from typing import Any, Tuple

import jax.numpy as jnp

PyTree = Any


class ServerAggregator(ABC):
    """Override ``aggregate``; the before/after hooks are optional."""

    def on_before_aggregation(
            self, update_matrix: jnp.ndarray, weights: jnp.ndarray
    ) -> Tuple[jnp.ndarray, jnp.ndarray]:
        return update_matrix, weights

    @abstractmethod
    def aggregate(self, update_matrix: jnp.ndarray,
                  weights: jnp.ndarray) -> jnp.ndarray:
        """[K, D] stacked client updates + [K] weights -> [D] aggregate."""

    def on_after_aggregation(self, agg_vec: jnp.ndarray) -> jnp.ndarray:
        return agg_vec
