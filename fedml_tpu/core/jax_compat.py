"""Version compatibility for jax APIs the framework uses.

The codebase targets the modern surface (``jax.shard_map`` with
``check_vma``); older jaxlib builds (< 0.6) only ship
``jax.experimental.shard_map.shard_map`` whose equivalent knob is spelled
``check_rep``. Import ``shard_map`` from here instead of from jax so both
generations of the runtime work unchanged.
"""

from __future__ import annotations

import jax

if hasattr(jax, "shard_map"):
    shard_map = jax.shard_map
else:
    from jax.experimental.shard_map import shard_map as _experimental_sm

    def shard_map(f, *, mesh, in_specs, out_specs, check_vma=True, **kw):
        return _experimental_sm(f, mesh=mesh, in_specs=in_specs,
                                out_specs=out_specs, check_rep=check_vma,
                                **kw)
