"""Workload-aware scheduling (reference ``core/schedule/``)."""

from .seq_train_scheduler import (RuntimeEstimator, SeqTrainScheduler,
                                  balanced_schedule)

__all__ = ["SeqTrainScheduler", "RuntimeEstimator", "balanced_schedule"]
