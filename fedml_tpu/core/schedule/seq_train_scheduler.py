"""Workload-aware client -> device scheduling.

Parity target: reference ``core/schedule/seq_train_scheduler.py:9``
(``SeqTrainScheduler.DP_schedule`` — dynamic-programming assignment of
heterogeneous client workloads to workers minimizing the makespan) and
``runtime_estimate.py:16`` (``t_sample_fit`` — per-(client, device) runtime
regression from observed history), used by ``fedavg_seq``
(``simulation/mpi/fedavg_seq/FedAVGAggregator.py:126-188``) and the NCCL
simulator's ``client_schedule``.

On TPU the per-client cost is nearly uniform *per step* (XLA compiles one
program), so cost ~ #batches x epochs; the scheduler still matters when
client datasets are heavily non-IID in size: the default round-robin
schedule puts a 10x-data client next to a 1x one and the lax.scan padding
wastes (10x - 1x) of every other chip's time. LPT (longest-processing-time)
greedy is within 4/3 of optimal and O(n log n) — the DP formulation of the
reference is kept for exact small cases.
"""

from __future__ import annotations

from typing import Dict, List, Sequence, Tuple

import numpy as np


class RuntimeEstimator:
    """Per-client runtime model fit from observed round times (reference
    ``t_sample_fit``): t(c, d) ~ alpha_d * n_c + beta_d, least-squares over
    the history of (client sample count, observed seconds) per device."""

    def __init__(self):
        self._obs: Dict[int, List[Tuple[float, float]]] = {}

    def record(self, device: int, n_samples: float, seconds: float) -> None:
        self._obs.setdefault(device, []).append((float(n_samples),
                                                 float(seconds)))

    def fit(self, device: int) -> Tuple[float, float]:
        """Returns (alpha, beta) for the device; (1, 0) before any data."""
        obs = self._obs.get(device, [])
        if len(obs) < 2:
            return 1.0, 0.0
        x = np.asarray([o[0] for o in obs])
        y = np.asarray([o[1] for o in obs])
        a, b = np.polyfit(x, y, 1)
        return float(max(a, 1e-9)), float(max(b, 0.0))

    def predict(self, device: int, n_samples: float) -> float:
        a, b = self.fit(device)
        return a * float(n_samples) + b


class SeqTrainScheduler:
    """Assign sampled clients (with per-client costs) to ``n_workers`` so
    the slowest worker finishes earliest."""

    def __init__(self, workloads: Sequence[float], n_workers: int,
                 mode: str = "lpt"):
        self.workloads = np.asarray(workloads, np.float64)
        self.n_workers = int(n_workers)
        self.mode = mode

    def schedule(self) -> Tuple[List[List[int]], float]:
        """Returns (per-worker client-index lists, makespan estimate)."""
        if self.mode == "dp" and len(self.workloads) <= 16 and self.n_workers == 2:
            return self._dp_two_workers()
        return self._lpt()

    def _lpt(self) -> Tuple[List[List[int]], float]:
        order = np.argsort(-self.workloads)
        loads = np.zeros(self.n_workers)
        out: List[List[int]] = [[] for _ in range(self.n_workers)]
        for i in order:
            w = int(np.argmin(loads))
            out[w].append(int(i))
            loads[w] += self.workloads[i]
        return out, float(loads.max())

    def _dp_two_workers(self) -> Tuple[List[List[int]], float]:
        """Exact partition for 2 workers via subset-sum DP (the reference's
        DP_schedule specialization that is actually optimal)."""
        total = self.workloads.sum()
        scale = 1000.0 / max(total, 1e-9)
        w = np.round(self.workloads * scale).astype(int)
        target = int(w.sum()) // 2
        reach = {0: []}
        for i, wi in enumerate(w):
            new = {}
            for s, items in reach.items():
                s2 = s + int(wi)
                if s2 <= target and s2 not in reach and s2 not in new:
                    new[s2] = items + [i]
            reach.update(new)
        best = max(reach)
        a = reach[best]
        b = [i for i in range(len(w)) if i not in a]
        la = float(self.workloads[a].sum()) if a else 0.0
        lb = float(self.workloads[b].sum()) if b else 0.0
        return [a, b], max(la, lb)


def balanced_schedule(
    sampled: Sequence[int],
    client_costs: Sequence[float],
    n_devices: int,
) -> List[List[int]]:
    """LPT-balance sampled clients over devices by cost; returns per-device
    global-client-id lists (the engine maps them to local slots)."""
    costs = [float(client_costs[c]) for c in sampled]
    sched, _ = SeqTrainScheduler(costs, n_devices).schedule()
    return [[int(sampled[i]) for i in dev] for dev in sched]
