"""Device-mesh construction — the hardware vocabulary of the framework.

The reference scales FL by mapping clients onto processes/GPUs through MPI
ranks or a NCCL process group (``nccl/base_framework/common.py:106-146``).
The TPU-native equivalent is a named `jax.sharding.Mesh`: the ``client`` axis
carries FL round-level parallelism; ``data``/``fsdp``/``tensor``/``sp`` axes
carry intra-silo parallelism for large models (the DeepSpeed/DDP analogue,
reference ``ml/engine/ml_engine_adapter.py:302``, ``train/llm/distributed.py``).

All collectives ride these named axes via ``shard_map``/``pjit`` — XLA lowers
them to ICI/DCN transfers; there is no NCCL/MPI plumbing to manage.
"""

from __future__ import annotations

import math
from typing import Dict, List, Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..constants import AXIS_CLIENT, AXIS_DATA, AXIS_FSDP, AXIS_TENSOR


def build_mesh(
    mesh_shape: Optional[Dict[str, int]] = None,
    devices: Optional[Sequence[jax.Device]] = None,
) -> Mesh:
    """Build a named mesh.

    ``mesh_shape`` maps axis name → size, e.g. ``{"client": 8}`` or
    ``{"client": 16, "fsdp": 8}``. A size of ``-1`` means "the remainder of
    the device count". Default: all local devices on one ``client`` axis —
    the Parrot-NCCL topology (one client slot per chip).
    """
    devices = list(devices if devices is not None else jax.devices())
    n = len(devices)
    if not mesh_shape:
        mesh_shape = {AXIS_CLIENT: n}
    names: List[str] = list(mesh_shape.keys())
    sizes: List[int] = [int(s) for s in mesh_shape.values()]
    if sizes.count(-1) > 1:
        raise ValueError("at most one mesh axis may be -1")
    fixed = math.prod(s for s in sizes if s != -1)
    sizes = [n // fixed if s == -1 else s for s in sizes]
    if math.prod(sizes) != n:
        raise ValueError(f"mesh shape {dict(zip(names, sizes))} != {n} devices")
    dev_array = np.asarray(devices).reshape(sizes)
    return Mesh(dev_array, axis_names=tuple(names))


def client_axis_size(mesh: Mesh) -> int:
    return mesh.shape.get(AXIS_CLIENT, 1)


def replicated(mesh: Mesh) -> NamedSharding:
    """Sharding for globally-replicated state (the broadcast of
    ``nccl/base_framework/common.py:222`` is free replication here)."""
    return NamedSharding(mesh, P())


def client_sharded(mesh: Mesh, ndim: int = 1) -> NamedSharding:
    """Shard leading axis over ``client``; used for per-client stacked data
    and schedule tensors."""
    return NamedSharding(mesh, P(AXIS_CLIENT, *([None] * (ndim - 1))))


def data_sharded(mesh: Mesh, ndim: int = 2) -> NamedSharding:
    """Batch-axis sharding over the ``data`` axis (intra-silo DDP analogue,
    reference ``ml/engine/ml_engine_adapter.py:273``)."""
    axis = AXIS_DATA if AXIS_DATA in mesh.shape else None
    return NamedSharding(mesh, P(axis, *([None] * (ndim - 1))))


def fsdp_param_sharding(mesh: Mesh, shape: Tuple[int, ...]) -> NamedSharding:
    """ZeRO-3-style parameter sharding: shard the largest divisible axis over
    ``fsdp`` (reference DeepSpeed path ``train/llm/distributed.py:54-70``)."""
    if AXIS_FSDP not in mesh.shape:
        return NamedSharding(mesh, P())
    size = mesh.shape[AXIS_FSDP]
    best = None
    for i, dim in sorted(enumerate(shape), key=lambda t: -t[1]):
        if dim % size == 0:
            best = i
            break
    spec = [None] * len(shape)
    if best is not None:
        spec[best] = AXIS_FSDP
    return NamedSharding(mesh, P(*spec))


def logical_sharding_rules() -> List[Tuple[str, Optional[str]]]:
    """flax logical-axis → mesh-axis rules for the LLM path (TP + FSDP)."""
    return [
        ("batch", AXIS_DATA),
        ("embed", AXIS_FSDP),
        ("mlp", AXIS_TENSOR),
        ("heads", AXIS_TENSOR),
        ("kv", None),
        ("vocab", AXIS_TENSOR),
    ]
