"""Adaptive keep-ratio schedules for the wire pipeline (ISSUE 19).

The PR 5 ``ClientStatsStore`` already tracks per-silo upload latency
(EMA) and a Beta dropout posterior. When ``comm_compression_adaptive``
is on, the server picks the next round's sparsification keep-ratio from
those observations — tighter wire when uplinks run slow or flaky,
looser (more signal per round) when the cohort is healthy — clamped to
``[ratio_min, ratio_max]``. The chosen ratio rides the sync message so
client uplinks and the server decoder agree per round; with the knob
off nothing is added to the wire.

Deterministic: same stats → same ratio (no RNG), so resumed runs pick
identical schedules.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Optional, Sequence

import numpy as np

__all__ = ["AdaptiveRatioBounds", "adaptive_keep_ratio"]

# ClientStatsStore's dropout prior is Beta(1, 4) → posterior mean 0.2
# before any observation; pressure is measured as excess over the prior.
_DROP_PRIOR_MEAN = 0.2


@dataclass(frozen=True)
class AdaptiveRatioBounds:
    """Configured bounds for the per-round keep-ratio."""

    ratio_min: float
    ratio_max: float
    latency_budget_s: Optional[float] = None  # uplink latency considered "full pressure"

    def __post_init__(self) -> None:
        if not (0.0 < self.ratio_min <= self.ratio_max <= 1.0):
            raise ValueError(
                f"need 0 < ratio_min <= ratio_max <= 1, got "
                f"[{self.ratio_min}, {self.ratio_max}]")
        if self.latency_budget_s is not None and self.latency_budget_s <= 0:
            raise ValueError("latency_budget_s must be positive")


def adaptive_keep_ratio(bounds: AdaptiveRatioBounds, stats,
                        ranks: Sequence[int]) -> float:
    """Pick the round's keep-ratio from observed upload latency and the
    dropout posterior of ``ranks``.

    Pressure in [0, 1] is the max of two signals: how close the slowest
    observed silo runs to the latency budget, and how far the worst
    dropout posterior sits above its prior. ``ratio = ratio_max -
    (ratio_max - ratio_min) * pressure`` — unobserved cohorts (all-NaN
    latency, prior-only posteriors) get ``ratio_max``.
    """
    ranks = list(ranks)
    if not ranks or stats is None:
        return bounds.ratio_max
    lat_frac = 0.0
    if bounds.latency_budget_s is not None:
        lat = np.asarray(stats.latency_for(ranks), np.float64)
        seen = lat[np.isfinite(lat)]
        if seen.size:
            lat_frac = float(np.clip(
                seen.max() / bounds.latency_budget_s, 0.0, 1.0))
    drop = np.asarray(stats.dropout_posterior_mean(ranks), np.float64)
    drop_frac = float(np.clip(
        (drop.max(initial=0.0) - _DROP_PRIOR_MEAN) / (1.0 - _DROP_PRIOR_MEAN),
        0.0, 1.0))
    pressure = max(lat_frac, drop_frac)
    ratio = bounds.ratio_max - (bounds.ratio_max - bounds.ratio_min) * pressure
    return float(np.clip(ratio, bounds.ratio_min, bounds.ratio_max))
