"""``core.wire`` — the unified secure-and-compressed wire pipeline
(ISSUE 19): one composable encode seam (delta → sparsify/quantize →
mask → frame) shared by cross-silo sync/async, hierarchical,
decentralized/gossip, cross-device, and the SecAgg/LSA masked paths.

See :mod:`.pipeline` for the stage map, :mod:`.field_quant` for the
lane-packed GF(2**31 - 1) quantization that makes compression
SecAgg-compatible, and :mod:`.adaptive` for stats-driven keep-ratio
schedules. All knobs default off with byte-identical wire.
"""

from .adaptive import AdaptiveRatioBounds, adaptive_keep_ratio
from .field_quant import (FIELD_P, LANE_BITS_CHOICES, LanePlan,
                          field_encode, lane_dequantize_sum, lane_pack,
                          lane_quantize, lane_unpack_sum, plan_for,
                          suggest_scale)
from .pipeline import (STAGE_FRAMED, STAGE_MASKED, STAGE_RAW,
                       STAGE_SPARSIFIED, EncodedUpdate, decode_update,
                       encode_update, mask_packed, payload_nbytes,
                       record_update_stages, unmask_sum)
from .state import (pack_optional_vec, unpack_optional_vec,
                    wire_checkpointer, wire_state_template)

__all__ = [
    "AdaptiveRatioBounds", "adaptive_keep_ratio",
    "FIELD_P", "LANE_BITS_CHOICES", "LanePlan", "field_encode",
    "lane_dequantize_sum", "lane_pack", "lane_quantize",
    "lane_unpack_sum", "plan_for", "suggest_scale",
    "STAGE_FRAMED", "STAGE_MASKED", "STAGE_RAW", "STAGE_SPARSIFIED",
    "EncodedUpdate", "decode_update", "encode_update", "mask_packed",
    "payload_nbytes", "record_update_stages", "unmask_sum",
    "pack_optional_vec", "unpack_optional_vec", "wire_checkpointer",
    "wire_state_template",
]
