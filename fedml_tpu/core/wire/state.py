"""Wire-pipeline state joins the round checkpoint (ISSUE 19 satellite).

Error-feedback compression is stateful: each sender carries a residual
of everything its sparsifier dropped, and each decoder tracks the base
the next delta applies to. A crash that loses the residual silently
drops accumulated (unsent) gradient mass; one that loses the base
corrupts every later delta. This module gives the cross-silo managers
(and the async server's per-sender pour residuals) a fixed-template
``RoundCheckpointer`` slot for exactly that state, reusing the existing
``checkpoint_dir`` / ``checkpoint_every_rounds`` knobs — off by
default, and resume-vs-uninterrupted parity is pinned in
``tests/test_wire.py``.
"""

from __future__ import annotations

import os
from typing import Dict, Optional, Sequence, Tuple

import numpy as np

from ..checkpoint import RoundCheckpointer

__all__ = ["wire_checkpointer", "wire_state_template", "pack_optional_vec",
           "unpack_optional_vec"]


def wire_checkpointer(args, role: str) -> Optional[RoundCheckpointer]:
    """A checkpointer for one manager's wire state, namespaced under the
    session's ``checkpoint_dir`` (``wire_<role>/``) so it never collides
    with the engine's model checkpoints. None when checkpointing is off."""
    directory = getattr(args, "checkpoint_dir", None)
    every = int(getattr(args, "checkpoint_every_rounds", 0) or 0)
    if not directory or every <= 0:
        return None
    return RoundCheckpointer(os.path.join(str(directory), f"wire_{role}"),
                             every_rounds=every)


def pack_optional_vec(vec, d: int) -> Tuple[np.ndarray, np.ndarray]:
    """``(set_flag, f32[d])`` pair for a maybe-None vector — orbax
    templates need fixed shapes, and a fresh manager's residual/base are
    legitimately None until first use."""
    if vec is None:
        return np.zeros((), np.int32), np.zeros((d,), np.float32)
    return np.ones((), np.int32), np.asarray(vec, np.float32).reshape(d)


def unpack_optional_vec(flag, arr) -> Optional[np.ndarray]:
    return np.asarray(arr, np.float32) if int(flag) else None


def wire_state_template(d: int, vecs: Sequence[str],
                        matrices: Dict[str, int] = None) -> Dict:
    """Fixed-shape restore template: a round cursor, ``(flag, [d])``
    slots for each named vector, and optional ``[n, d]`` matrix slots
    (async per-sender residuals)."""
    out = {"round": np.zeros((), np.int32)}
    for name in vecs:
        out[f"{name}_set"] = np.zeros((), np.int32)
        out[name] = np.zeros((d,), np.float32)
    for name, n in (matrices or {}).items():
        out[f"{name}_set"] = np.zeros((n,), np.int32)
        out[name] = np.zeros((n, d), np.float32)
    return out
