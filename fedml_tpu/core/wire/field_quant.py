"""Lane-packed quantization into GF(2**31 - 1) for SecAgg-compatible
compression (ISSUE 19).

Secure aggregation (Bonawitz et al., CCS'17; ``core/mpc/secagg.py``)
sums *masked* vectors mod ``p = 2**31 - 1`` — 4 B per coordinate on the
wire regardless of model precision. This module quantizes client deltas
to ``b``-bit unsigned lanes and packs several lanes per uint32 field
element so the masked vector shrinks by the lane count ``L`` while the
masked sum stays **bit-exact**:

* lane width  ``w = b + ceil(log2(k_max))`` reserves headroom for the
  sum of up to ``k_max`` clients per lane;
* lanes/elem  ``L = 30 // w`` keeps every packed element — and the
  *integer* sum of ``k_max`` packed elements — strictly below
  ``2**30 < p``, so mod-p addition never wraps and per-lane sums can be
  recovered with plain shifts.

Overflow proof (the property ``test_wire.py`` pins): each lane value is
in ``[0, 2**b - 1]`` (signed values offset by ``2**(b-1)``), so a lane
sum over ``K <= k_max`` clients is at most ``k_max * (2**b - 1)
<= 2**w - 1`` — lanes never carry into each other — and the packed sum
is at most ``sum_j (2**w - 1) * 2**(w*j) = 2**(w*L) - 1 <= 2**30 - 1
< p``. Hence ``sum_i (q_i + m_i) - sum_i m_i  (mod p)`` equals the true
integer sum of the packed vectors, and unmasking is exact: masks cancel
bit-for-bit, quantization is the only lossy step (stochastic rounding +
clipping, both absorbed by the caller's error-feedback residual).

Wire cost per f32 coordinate: ``4 / L`` bytes — e.g. 4-bit lanes with
``k_max = 4`` give ``w = 6, L = 5`` → 0.8 B/coord (5x); 8-bit lanes
with ``k_max = 16`` give ``w = 12, L = 2`` → 2 B/coord.
"""

from __future__ import annotations

import math
from dataclasses import dataclass
from typing import Optional, Tuple

import numpy as np

# Packed elements (and their k_max-sums) are kept below 2**30; the field
# prime is 2**31 - 1, so sums mod p equal the true integer sums.
_PACK_BITS = 30
FIELD_P = int(2**31 - 1)

LANE_BITS_CHOICES = (4, 8, 16)


@dataclass(frozen=True)
class LanePlan:
    """Static packing geometry shared by every client and the server for
    one secure-aggregation session. ``bits`` is the signed quantization
    width per value; ``k_max`` the maximum number of summands a lane
    must hold without carrying."""

    bits: int
    k_max: int

    def __post_init__(self) -> None:
        if self.bits not in LANE_BITS_CHOICES:
            raise ValueError(
                f"lane bits must be one of {LANE_BITS_CHOICES}, "
                f"got {self.bits}")
        if self.k_max < 1:
            raise ValueError(f"k_max must be >= 1, got {self.k_max}")
        if self.width > _PACK_BITS:
            raise ValueError(
                f"lane width {self.width} (= {self.bits} bits + headroom "
                f"for k_max={self.k_max}) exceeds {_PACK_BITS}-bit field "
                "budget — lower bits or k_max")

    @property
    def width(self) -> int:
        """Per-lane width incl. sum headroom: ``b + ceil(log2(k_max))``."""
        return self.bits + max(0, math.ceil(math.log2(self.k_max)))

    @property
    def lanes(self) -> int:
        """Quantized values packed per uint32 field element."""
        return _PACK_BITS // self.width

    @property
    def offset(self) -> int:
        """Unsigned offset: signed value ``v`` is stored as ``v + 2**(b-1)``."""
        return 1 << (self.bits - 1)

    @property
    def qmax(self) -> int:
        """Largest signed magnitude representable: ``2**(b-1) - 1``."""
        return (1 << (self.bits - 1)) - 1

    def packed_len(self, d: int) -> int:
        return -(-d // self.lanes)

    def bytes_per_coord(self) -> float:
        """Wire bytes per f32 coordinate of the masked vector."""
        return 4.0 / self.lanes

    def to_wire(self) -> dict:
        return {"bits": int(self.bits), "k_max": int(self.k_max)}

    @staticmethod
    def from_wire(obj: dict) -> "LanePlan":
        return LanePlan(bits=int(obj["bits"]), k_max=int(obj["k_max"]))


def plan_for(bits: int, k_max: int) -> LanePlan:
    return LanePlan(bits=bits, k_max=k_max)


def suggest_scale(max_abs: float, plan: LanePlan) -> float:
    """Scale such that ``max_abs`` lands on the clip boundary."""
    return float(max(max_abs, 1e-30)) / float(plan.qmax)


def lane_quantize(x: np.ndarray, scale: float, plan: LanePlan,
                  rng: np.random.Generator,
                  ) -> Tuple[np.ndarray, np.ndarray]:
    """Stochastically round ``x / scale`` to signed ``bits``-wide ints,
    clip, offset to unsigned, and pack ``plan.lanes`` values per uint32.

    Returns ``(packed uint32 [packed_len], residual f32 [d])`` where the
    residual is ``x - scale * q_signed`` — the exact quantization +
    clipping error, for the caller's error-feedback accumulator.
    """
    x = np.asarray(x, np.float32)
    y = x.astype(np.float64) / float(scale)
    q = np.floor(y + rng.random(y.shape)).astype(np.int64)
    q = np.clip(q, -plan.offset, plan.qmax)
    residual = (x.astype(np.float64) - float(scale) * q).astype(np.float32)
    u = (q + plan.offset).astype(np.uint64)  # [0, 2**bits)
    packed = lane_pack(u, plan)
    return packed, residual


def lane_pack(u: np.ndarray, plan: LanePlan) -> np.ndarray:
    """Pack unsigned lane values ``u`` (each < 2**bits) into uint32
    field elements. Tail lanes are padded with ``plan.offset`` (encoded
    zero) so they dequantize to exactly 0 after the per-lane ``K *
    offset`` subtraction."""
    u = np.asarray(u, np.uint64)
    L, w = plan.lanes, plan.width
    d = u.shape[0]
    dp = plan.packed_len(d)
    full = np.full(dp * L, plan.offset, np.uint64)
    full[:d] = u
    lanes = full.reshape(dp, L)
    shifts = (np.arange(L, dtype=np.uint64) * np.uint64(w))
    packed = (lanes << shifts[None, :]).sum(axis=1, dtype=np.uint64)
    return packed.astype(np.uint32)


def lane_unpack_sum(total: np.ndarray, k: int, plan: LanePlan,
                    d: int) -> np.ndarray:
    """Recover per-lane signed sums from ``total = sum_i packed_i``
    (mod p — exact by the overflow bound), for ``k`` actual summands.
    Returns int64 ``[d]``: ``sum_i q_signed_i`` per coordinate."""
    if k > plan.k_max:
        raise ValueError(
            f"{k} summands exceed the lane plan's k_max={plan.k_max} — "
            "lane sums may have carried; aborting rather than decoding "
            "corrupt lanes")
    t = np.asarray(total, np.uint64)
    L, w = plan.lanes, plan.width
    mask = np.uint64((1 << w) - 1)
    lanes = np.empty((t.shape[0], L), np.int64)
    for j in range(L):
        lanes[:, j] = ((t >> np.uint64(w * j)) & mask).astype(np.int64)
    lanes -= int(k) * plan.offset
    return lanes.reshape(-1)[:d]


def lane_dequantize_sum(total: np.ndarray, k: int, scale: float,
                        plan: LanePlan, d: int) -> np.ndarray:
    """Float sum of the ``k`` quantized vectors whose packed mod-p sum
    is ``total``: unpack lane sums, remove the ``k * offset`` bias, and
    rescale."""
    s = lane_unpack_sum(total, k, plan, d)
    return (s.astype(np.float64) * float(scale)).astype(np.float32)


def field_encode(delta: np.ndarray, scale: float, plan: LanePlan,
                 residual: Optional[np.ndarray],
                 rng: np.random.Generator,
                 ) -> Tuple[np.ndarray, np.ndarray]:
    """Error-feedback wrapper around :func:`lane_quantize`: adds the
    carried residual before quantizing and returns the new residual.
    This is the sparsify/quantize stage of the secure uplink — the
    caller masks the returned packed vector (mod p) and ships it."""
    delta = np.asarray(delta, np.float32)
    comp = delta if residual is None else delta + residual
    return lane_quantize(comp, scale, plan, rng)
