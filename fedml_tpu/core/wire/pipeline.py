"""The composable wire pipeline: delta → sparsify/quantize → mask →
frame (ISSUE 19).

Every transport funnels its model-bearing messages through the same
four stages; each stage is a small stateless function here (state —
error-feedback residuals, broadcast bases — stays on the owning
manager, which is also what the checkpoint satellites persist):

====================  =======================================================
stage                 implementation
====================  =======================================================
delta                 subtract the shared base the receiver already holds
                      (``encode_update(base=...)`` / ``decode_update``)
sparsify/quantize     QSGD / top-k / rand-k with per-sender error feedback
                      (``utils/compression.ef_compress_vec`` — wire format
                      unchanged, so knob-off bytes stay pinned), or lane-
                      packed field quantization for masked uplinks
                      (:mod:`.field_quant`)
mask                  pairwise + self masks mod p (``core/mpc/secagg``) —
                      applied to the *packed* field vector, which is what
                      makes compression SecAgg-compatible
frame                 msgpack framing in ``Message.encode`` (ext-type numpy)
====================  =======================================================

When no knob is on, ``encode_update`` returns ``payload=None`` and the
caller ships its dense tree exactly as before — byte-identity on every
transport is pinned by ``tests/test_comm_compression.py`` and
``tests/test_wire.py``.

The per-stage byte ledger (``record_update_stages``) attributes raw vs
post-sparsify vs post-mask bytes by message type into ``WIRE_STATS``
and ``core/obs`` metrics so ``metrics_snapshot``/``trace_report`` show
where the wire bytes went.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Optional

import numpy as np

from ...utils.compression import (CommCompressionSpec, decompress_vec,
                                  ef_compress_vec, is_compressed_payload)
from ..distributed.communication.message import WIRE_STATS, dumps_tree
from .field_quant import LanePlan, field_encode, lane_dequantize_sum

__all__ = [
    "EncodedUpdate", "encode_update", "decode_update", "payload_nbytes",
    "record_update_stages", "mask_packed", "unmask_sum",
]

# Ledger stage names (satellite: bytes per pipeline stage by msg type).
STAGE_RAW = "raw"                # dense f32 equivalent of the update
STAGE_SPARSIFIED = "sparsified"  # after sparsify/quantize (blob bytes)
STAGE_MASKED = "masked"          # after mod-p masking (field vector bytes)
STAGE_FRAMED = "framed"          # full encoded message (msgpack framing)


@dataclass
class EncodedUpdate:
    """Result of the sparsify/quantize stage for one uplink."""

    payload: Optional[dict]          # compression blob; None = ship dense
    residual: Optional[np.ndarray]   # updated error-feedback residual
    raw_bytes: int                   # dense f32 bytes of the update
    payload_bytes: int               # wire bytes of the blob (0 if dense)


def payload_nbytes(obj) -> int:
    """Honest wire size of a payload: its msgpack framing length."""
    if obj is None:
        return 0
    return len(dumps_tree(obj))


def encode_update(vec: np.ndarray, *, base: Optional[np.ndarray] = None,
                  spec: Optional[CommCompressionSpec] = None,
                  residual: Optional[np.ndarray] = None,
                  rng=None, msg_type=None) -> EncodedUpdate:
    """Delta + sparsify/quantize stages for one model update.

    ``base`` is the reference the receiver already holds (the broadcast
    global for sync uplinks, the sender's previous reconstruction for
    gossip); ``None`` means the update is already a delta — or, with
    ``spec=None``, that the caller ships dense and this is a no-op that
    only returns byte accounting.
    """
    vec = np.asarray(vec, np.float32)
    raw = int(vec.nbytes)
    if spec is None or spec.method is None:
        return EncodedUpdate(None, residual, raw, 0)
    delta = vec if base is None else vec - np.asarray(base, np.float32)
    blob, new_res = ef_compress_vec(delta, residual, spec, rng)
    nbytes = payload_nbytes(blob)
    if msg_type is not None:
        record_update_stages(msg_type, raw=raw, sparsified=nbytes)
    return EncodedUpdate(blob, new_res, raw, nbytes)


def decode_update(payload, *, base: Optional[np.ndarray] = None,
                  ) -> np.ndarray:
    """Inverse of :func:`encode_update`'s sparsify stage: blob → delta,
    plus the receiver's base when given."""
    if not is_compressed_payload(payload):
        raise ValueError("decode_update expects a compression blob; "
                         "dense payloads never enter the pipeline")
    delta = decompress_vec(payload)
    if base is None:
        return delta
    return (np.asarray(base, np.float32) + delta).astype(np.float32)


def record_update_stages(msg_type, *, raw: Optional[int] = None,
                         sparsified: Optional[int] = None,
                         masked: Optional[int] = None) -> None:
    """Attribute bytes to pipeline stages for one message type. The
    framing stage is recorded by ``Message.encode`` itself (total bytes
    by type), so framing overhead = framed − the last pre-frame stage."""
    for stage, nbytes in ((STAGE_RAW, raw), (STAGE_SPARSIFIED, sparsified),
                          (STAGE_MASKED, masked)):
        if nbytes is not None:
            WIRE_STATS.record_stage(msg_type, stage, int(nbytes))


def mask_packed(packed: np.ndarray, mask_total: np.ndarray) -> np.ndarray:
    """Mask stage: add the combined pairwise/self mask mod p to the
    lane-packed field vector. Identical math to the dense SecAgg path —
    lanes need no special casing because mod-p sums of packed elements
    are exact (see :mod:`.field_quant`)."""
    p = np.uint64(2**31 - 1)
    q = np.asarray(packed, np.uint64)
    m = np.asarray(mask_total, np.uint64)
    return ((q + m) % p).astype(np.uint32)


def unmask_sum(total: np.ndarray, k: int, scale: float, plan: LanePlan,
               d: int) -> np.ndarray:
    """Decode stage for the server: ``total`` is the unmasked mod-p sum
    of ``k`` lane-packed client vectors; returns the float sum of the
    quantized updates (bit-identical to summing the unmasked packed
    vectors directly — the acceptance property)."""
    return lane_dequantize_sum(total, k, scale, plan, d)


__all__ += ["field_encode", "LanePlan"]
