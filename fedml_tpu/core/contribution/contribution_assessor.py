"""Client contribution metrics over one FL round.

Parity targets: reference ``core/contribution/gtg_shapley_value.py`` (150 —
truncated Monte-Carlo Shapley with within-round truncation + between-round
convergence), ``leave_one_out.py`` (127).

TPU-native design: the round utility v(S) = metric(params + weighted-avg of
S's updates) is evaluated with ONE jitted function taking a client
*inclusion mask*, so every coalition evaluation reuses the same compiled
program; the Monte-Carlo permutation loop stays on the host (tiny) while all
FLOPs (aggregate + eval forward pass) stay on device.

The LOO/GTG drivers therefore only ever see ``v(mask) -> float``
(:func:`leave_one_out_values` / :func:`gtg_shapley_values`) — which is what
lets the TPU engine swap in its SHARDED subset-evaluation kernel (masked
aggregation over the feature-sharded update matrix + eval over a sharded
held-out set, see ``TPUSimulator._assess_contribution_fused``) without this
module knowing about meshes: only the final [K] scores cross to the host.
"""

from __future__ import annotations

import logging
from typing import Any, Callable, Dict, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

logger = logging.getLogger(__name__)

PyTree = Any


def _make_subset_value_fn(eval_fn: Callable[[PyTree], jnp.ndarray]):
    """Build v(mask): aggregate the masked subset of updates onto the global
    params and evaluate. jitted once; mask is the only changing input."""

    def value(params, stacked_updates, weights, mask):
        w = weights * mask
        denom = jnp.maximum(jnp.sum(w), 1e-12)

        def avg(leaf):
            ww = (w / denom).reshape((-1,) + (1,) * (leaf.ndim - 1))
            return jnp.sum(leaf * ww.astype(leaf.dtype), axis=0)

        agg = jax.tree_util.tree_map(avg, stacked_updates)
        cand = jax.tree_util.tree_map(jnp.add, params, agg)
        return eval_fn(cand)

    return jax.jit(value)


def leave_one_out_values(value_of_mask: Callable[[jnp.ndarray], float],
                         k: int) -> np.ndarray:
    """LOO contribution over an opaque coalition-value callable
    ``value_of_mask([K] 0/1 mask) -> float``: v(N) - v(N \\ {i}) per
    client. The callable owns all device work (and any sharding)."""
    full = float(value_of_mask(jnp.ones(k)))
    out = np.zeros(k)
    for i in range(k):
        out[i] = full - float(value_of_mask(jnp.ones(k).at[i].set(0.0)))
    return out


def leave_one_out(
    params: PyTree,
    stacked_updates: PyTree,
    weights: jnp.ndarray,
    eval_fn: Callable[[PyTree], jnp.ndarray],
) -> np.ndarray:
    """LOO over stacked update pytrees (builds the jitted subset-value fn
    and defers to :func:`leave_one_out_values`)."""
    k = int(weights.shape[0])
    vfn = _make_subset_value_fn(eval_fn)
    return leave_one_out_values(
        lambda mask: vfn(params, stacked_updates, weights, mask), k)


def gtg_shapley_values(
    value_of_mask: Callable[[jnp.ndarray], float],
    k: int,
    max_perms: int = 20,
    truncation_eps: float = 1e-4,
    convergence_eps: float = 0.01,
    seed: int = 0,
) -> np.ndarray:
    """Guided-truncated-gradient Shapley (reference
    ``gtg_shapley_value.py``) over an opaque coalition-value callable:
    Monte-Carlo over permutations with within-permutation truncation (stop
    scanning once the remaining marginal gain is below ``truncation_eps``)
    and between-permutation convergence (stop when the running Shapley
    estimate moves < ``convergence_eps``)."""
    vfn = lambda mask: float(value_of_mask(mask))
    v_empty = vfn(jnp.zeros(k))
    v_full = vfn(jnp.ones(k))
    rng = np.random.RandomState(seed)
    phi = np.zeros(k)
    count = 0
    prev = None
    for t in range(max_perms):
        # guided: first permutation is the round order; later ones random
        perm = np.arange(k) if t == 0 else rng.permutation(k)
        mask = np.zeros(k, np.float32)
        v_prev = v_empty
        for pos, i in enumerate(perm):
            if abs(v_full - v_prev) < truncation_eps:
                # truncation: remaining clients get zero marginal this pass
                break
            mask[i] = 1.0
            v_cur = vfn(jnp.asarray(mask))
            phi[i] += v_cur - v_prev
            v_prev = v_cur
        count += 1
        est = phi / count
        if prev is not None and np.max(np.abs(est - prev)) < convergence_eps:
            break
        prev = est
    return phi / max(count, 1)


def gtg_shapley(
    params: PyTree,
    stacked_updates: PyTree,
    weights: jnp.ndarray,
    eval_fn: Callable[[PyTree], jnp.ndarray],
    max_perms: int = 20,
    truncation_eps: float = 1e-4,
    convergence_eps: float = 0.01,
    seed: int = 0,
) -> np.ndarray:
    """GTG-Shapley over stacked update pytrees (builds the jitted
    subset-value fn and defers to :func:`gtg_shapley_values`)."""
    k = int(weights.shape[0])
    vfn = _make_subset_value_fn(eval_fn)
    return gtg_shapley_values(
        lambda mask: vfn(params, stacked_updates, weights, mask), k,
        max_perms=max_perms, truncation_eps=truncation_eps,
        convergence_eps=convergence_eps, seed=seed)


class ContributionAssessorManager:
    """Configured from args; called by the server after aggregation
    (reference ``ServerAggregator.assess_contribution``)."""

    def __init__(self, args):
        self.args = args
        self.method = str(getattr(args, "contribution_method", None) or "").lower()
        self.enabled = self.method in ("loo", "leave_one_out", "gtg",
                                       "gtg_shapley", "shapley")
        self.history: List[Dict[str, Any]] = []

    def assess_values(
        self,
        value_of_mask: Callable[[jnp.ndarray], float],
        k: int,
        client_ids: Optional[Sequence[int]] = None,
        round_idx: int = 0,
    ) -> Optional[np.ndarray]:
        """Assess over an opaque coalition-value callable — the entry point
        the fused TPU path uses (its ``value_of_mask`` evaluates on the
        feature-sharded update matrix; only scalars reach the host)."""
        if not self.enabled:
            return None
        if self.method in ("loo", "leave_one_out"):
            vals = leave_one_out_values(value_of_mask, k)
        else:
            vals = gtg_shapley_values(value_of_mask, k,
                                      max_perms=int(getattr(
                                          self.args, "shapley_max_perms",
                                          20) or 20))
        return self._record(vals, client_ids, round_idx)

    def assess(
        self,
        params: PyTree,
        stacked_updates: PyTree,
        weights: jnp.ndarray,
        eval_fn: Callable[[PyTree], jnp.ndarray],
        client_ids: Optional[Sequence[int]] = None,
        round_idx: int = 0,
    ) -> Optional[np.ndarray]:
        if not self.enabled:
            return None
        vfn = _make_subset_value_fn(eval_fn)
        return self.assess_values(
            lambda mask: vfn(params, stacked_updates, weights, mask),
            int(weights.shape[0]), client_ids=client_ids,
            round_idx=round_idx)

    def _record(self, vals: np.ndarray, client_ids, round_idx: int
                ) -> np.ndarray:
        self.history.append({
            "round": round_idx,
            "client_ids": list(client_ids) if client_ids is not None
            else list(range(len(vals))),
            "contributions": vals.tolist(),
        })
        logger.info("round %d contributions: %s", round_idx,
                    np.round(vals, 4).tolist())
        return vals
