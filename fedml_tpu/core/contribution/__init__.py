"""Contribution assessment (reference ``core/contribution/``): GTG-Shapley,
leave-one-out, and the manager consulted from the server aggregation hook
(``ContributionAssessorManager``, reference
``contribution_assessor_manager.py``; ``ServerAggregator.assess_contribution``
hook ``server_aggregator.py:105``)."""

from .contribution_assessor import (ContributionAssessorManager,
                                    gtg_shapley, gtg_shapley_values,
                                    leave_one_out, leave_one_out_values)

__all__ = ["ContributionAssessorManager", "gtg_shapley",
           "gtg_shapley_values", "leave_one_out", "leave_one_out_values"]
