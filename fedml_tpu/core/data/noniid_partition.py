"""Non-IID partitioning (host-side numpy).

Parity target: ``core/data/noniid_partition.py:1-124`` of the reference —
hetero Dirichlet partition with per-client balancing — plus the ``homo``
method used throughout ``data/*`` loaders. Output is a dict
client_idx → np.ndarray of sample indices; downstream everything is padded
into static shapes (see ``fedml_tpu/data/containers.py``).
"""

from __future__ import annotations

from typing import Dict, Optional

import numpy as np


def homo_partition(n_samples: int, num_clients: int,
                   rng: np.random.RandomState) -> Dict[int, np.ndarray]:
    idxs = rng.permutation(n_samples)
    return {i: np.sort(part) for i, part in enumerate(np.array_split(idxs, num_clients))}


def hetero_dirichlet_partition(
    labels: np.ndarray,
    num_clients: int,
    alpha: float,
    rng: Optional[np.random.RandomState] = None,
    min_size_floor: int = 1,
) -> Dict[int, np.ndarray]:
    """Dirichlet(alpha) label-skew partition. For each class, draw client
    proportions ~ Dir(alpha), capping clients already above the average share
    (the balancing trick of the reference's
    ``partition_class_samples_with_dirichlet_distribution``). Re-draws until
    every client has at least ``min_size_floor`` samples."""
    rng = rng or np.random.RandomState(0)
    n = labels.shape[0]
    classes = np.unique(labels)
    min_size = 0
    idx_batch = None
    while min_size < min_size_floor:
        idx_batch = [[] for _ in range(num_clients)]
        for k in classes:
            idx_k = np.where(labels == k)[0]
            rng.shuffle(idx_k)
            proportions = rng.dirichlet(np.repeat(alpha, num_clients))
            # balance: zero out clients that already hold >= fair share
            proportions = np.array([
                p * (len(ib) < n / num_clients)
                for p, ib in zip(proportions, idx_batch)])
            s = proportions.sum()
            if s <= 0:
                proportions = np.ones(num_clients) / num_clients
            else:
                proportions = proportions / s
            cuts = (np.cumsum(proportions) * len(idx_k)).astype(int)[:-1]
            for i, part in enumerate(np.split(idx_k, cuts)):
                idx_batch[i].extend(part.tolist())
        min_size = min(len(ib) for ib in idx_batch)
    out = {}
    for i in range(num_clients):
        arr = np.asarray(idx_batch[i], dtype=np.int64)
        rng.shuffle(arr)
        out[i] = arr
    return out


def shard_partition(
    labels: np.ndarray,
    num_clients: int,
    shards_per_client: int = 2,
    rng: Optional[np.random.RandomState] = None,
) -> Dict[int, np.ndarray]:
    """Pathological label-shard partition (McMahan et al. FedAvg paper; the
    reference's MNIST loader uses this shape): sort by label, cut into
    ``num_clients * shards_per_client`` shards, deal each client
    ``shards_per_client`` random shards — most clients see ~2 classes."""
    rng = rng or np.random.RandomState(0)
    order = np.argsort(labels, kind="stable")
    n_shards = num_clients * shards_per_client
    shards = np.array_split(order, n_shards)
    assignment = rng.permutation(n_shards)
    out = {}
    for i in range(num_clients):
        mine = assignment[i * shards_per_client:(i + 1) * shards_per_client]
        arr = np.concatenate([shards[s] for s in mine])
        rng.shuffle(arr)
        out[i] = arr
    return out


def partition(
    labels: np.ndarray,
    num_clients: int,
    method: str = "hetero",
    alpha: float = 0.5,
    seed: int = 0,
) -> Dict[int, np.ndarray]:
    rng = np.random.RandomState(seed)
    if method in ("homo", "iid"):
        return homo_partition(labels.shape[0], num_clients, rng)
    if method in ("hetero", "dirichlet", "noniid"):
        return hetero_dirichlet_partition(labels, num_clients, alpha, rng)
    if method in ("shards", "pathological"):
        return shard_partition(labels, num_clients, rng=rng)
    raise ValueError(f"unknown partition_method {method!r}")
