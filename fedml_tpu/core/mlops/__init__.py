"""Observability façade (reference ``core/mlops/`` 4.5k LoC).

Re-exports the reference's user-facing surface —
``mlops.init/log/event/log_metric/log_round_info/log_model/...``
(``core/mlops/__init__.py:99-1466``) — over pluggable local sinks instead of
the MQTT+platform pipeline: a JSON-lines event/metric log per run (the
replacement for the MQTT topics the reference publishes to), optional wandb
(gated — not installed here), and the JAX profiler for device-side traces
(the TPU-native replacement for the reference's wall-clock profiler events,
``mlops_profiler_event.py:74-97``).

System perf sampling (``mlops_device_perfs.py``) maps to a psutil sampler
thread; device utilization comes from jax memory stats.
"""

from __future__ import annotations

import atexit
import json
import logging
import os
import threading
import time
from typing import Any, Dict, Optional

# the obs planes hang off the same sink: mlops stays the user-facing
# façade and the JSONL funnel, core/obs owns tracing/metrics/profiling
# (obs only imports mlops lazily at emission time — no cycle)
from ..obs import metrics as obs_metrics
from ..obs import trace as obs_trace

logger = logging.getLogger(__name__)

_state: Dict[str, Any] = {"run_id": "0", "sink": None, "enabled": False,
                          "sys_thread": None}


class JsonSink:
    """Append-only JSON-lines sink — one file per run, thread-safe."""

    def __init__(self, path: str):
        os.makedirs(os.path.dirname(path), exist_ok=True)
        self._f = open(path, "a", buffering=1)
        self._lock = threading.Lock()
        atexit.register(self.close)

    def emit(self, record: Dict[str, Any]) -> None:
        with self._lock:
            self._f.write(json.dumps(record) + "\n")

    def close(self) -> None:
        try:
            self._f.close()
        except Exception:
            pass


def init(args) -> None:
    """(reference ``mlops.init`` :99) — wire sinks from args. Tracking is on
    by default (as in the reference) and off with ``enable_tracking: false``;
    an unwritable log dir degrades to disabled instead of failing init."""
    _state["run_id"] = str(getattr(args, "run_id", "0"))
    _state["enabled"] = bool(getattr(args, "enable_tracking", True))
    # observability knobs (core/obs): tracing + metrics cadence + device
    # profiling — configured here so every entry point that calls
    # mlops.init wires the whole layer in one place
    from .. import obs
    obs.configure(args)
    if not _state["enabled"]:
        _state["sink"] = None
        return
    log_dir = os.path.expanduser(
        getattr(args, "log_file_dir", None) or "~/.cache/fedml_tpu/logs")
    path = os.path.join(log_dir, f"run_{_state['run_id']}.jsonl")
    prev = _state.get("sink")
    if prev is not None:
        prev.close()
    try:
        _state["sink"] = JsonSink(path)
    except OSError as e:
        logger.warning("mlops sink unavailable (%s); tracking disabled", e)
        _state["sink"] = None
        _state["enabled"] = False
    # remote half of observability: tail+POST the run's JSONL to a log
    # server when configured (reference mlops_runtime_log_daemon.py:219).
    # A re-init for a new run stops (and flushes) the previous shipper —
    # otherwise every init leaks a polling thread for the process lifetime.
    prev_shipper = _state.pop("shipper", None)
    if prev_shipper is not None:
        prev_shipper.stop()
    log_url = (getattr(args, "log_server_url", None)
               or os.environ.get("FEDML_TPU_LOG_SERVER_URL"))
    if log_url and _state["sink"] is not None:
        from .log_daemon import start_log_shipper
        _state["shipper"] = start_log_shipper(
            path, log_url, run_id=_state["run_id"],
            device_id=str(getattr(args, "device_id", 0)))
    if bool(getattr(args, "sys_perf_profiling", False)):
        start_sys_perf()


def _emit(kind: str, payload: Dict[str, Any]) -> None:
    sink = _state.get("sink")
    if sink is None:
        return
    payload = dict(payload)
    payload.update({"kind": kind, "ts": time.time(),
                    "run_id": _state["run_id"]})
    sink.emit(payload)


def log(metrics: Dict[str, Any], step: Optional[int] = None) -> None:
    """(reference ``mlops.log`` :178)"""
    _emit("metric", {"metrics": metrics, "step": step})


def log_metric(metrics: Dict[str, Any], step: Optional[int] = None) -> None:
    log(metrics, step)


def log_round_info(total_rounds: int, round_idx: int) -> None:
    """(reference ``log_round_info`` :1004). Doubles as the metrics
    registry's round-boundary clock: every engine/server already calls
    it once per round, so the periodic ``metrics_snapshot`` JSONL flush
    rides it with zero extra wiring."""
    _emit("round", {"round_idx": round_idx, "total_rounds": total_rounds})
    obs_metrics.maybe_flush(int(round_idx))


def log_comm_round(round_idx: int, wire_bytes: int,
                   compression: Optional[str] = None,
                   by_type: Optional[Dict[str, Any]] = None) -> None:
    """Bytes-on-wire for one FL round, as recorded by the ``WireStats``
    ledger at the ``Message.encode`` seam (``wire_bytes`` is the diff of
    the ledger across the round; ``by_type`` optionally carries the
    per-message-type breakdown of a full snapshot)."""
    _emit("comm", {"round_idx": round_idx, "wire_bytes": int(wire_bytes),
                   "compression": compression, "by_type": by_type})


def log_chaos(round_idx: Optional[int] = None,
              injected: Optional[Dict[str, Any]] = None,
              observed: Optional[Dict[str, Any]] = None,
              link: Optional[Dict[str, Any]] = None,
              arrivals: Optional[list] = None,
              serving: Optional[Dict[str, Any]] = None) -> None:
    """Fault-ledger record from the chaos subsystem: what the
    :class:`~fedml_tpu.core.chaos.FaultPlan` injected this round vs what
    the runtime observed at the aggregation seam (or one link fault event).
    A tolerance bug shows up as the two disagreeing in the run log.

    ``arrivals`` carries a buffered-async pour's per-update records
    (client, staleness at aggregation time, arrival timestamp, dispatch
    version) — the raw material for reconstructing arrival distributions
    in post-mortems and the async bench."""
    rec: Dict[str, Any] = {}
    if round_idx is not None:
        rec["round_idx"] = int(round_idx)
    if injected is not None:
        rec["injected"] = injected
    if observed is not None:
        rec["observed"] = observed
    if link is not None:
        rec["link"] = link
    if serving is not None:
        rec["serving"] = serving
    if arrivals is not None:
        rec["arrivals"] = arrivals
        # pour-shaped records feed the staleness / buffer-occupancy
        # histograms (both async seams funnel through record_pour here)
        stal = [a.get("staleness", 0) for a in arrivals
                if isinstance(a, dict)]
        buffered = (observed or {}).get("buffered", 0)
        obs_metrics.record_pour(stal, int(buffered), len(arrivals))
    _emit("chaos", rec)


def log_selection(round_idx: int, strategy: str,
                  sampled: Optional[list] = None,
                  excluded: Optional[list] = None,
                  target_n: Optional[int] = None,
                  dropout_posterior: Optional[float] = None,
                  **extra: Any) -> None:
    """One participant-selection decision (core/selection): which clients
    the strategy scheduled, which it benched (reputation exclusions — the
    in-program-dropout path), the adaptive cohort target, and the pooled
    dropout posterior that sized it."""
    rec: Dict[str, Any] = {"round_idx": int(round_idx),
                           "strategy": str(strategy)}
    if sampled is not None:
        rec["sampled"] = [int(c) for c in sampled]
    if excluded is not None:
        rec["excluded"] = [int(c) for c in excluded]
    if target_n is not None:
        rec["target_n"] = int(target_n)
    if dropout_posterior is not None:
        rec["dropout_posterior"] = float(dropout_posterior)
    rec.update(extra)
    obs_metrics.record_selection(strategy, len(sampled or ()),
                                 len(excluded or ()))
    _emit("selection", rec)


def log_dispatch(name: str, wall_s: float, rounds: int = 1,
                 compiles: int = 0) -> None:
    """One device dispatch at the engine seam: host-side wall time of the
    dispatch call, how many FL rounds it carried (fused blocks > 1), and
    how many XLA compiles it triggered (the recompile counter — a steady
    state of 0 is the invariant; anything else is shape instability)."""
    obs_metrics.record_dispatch(name, wall_s, rounds, compiles)
    _emit("dispatch", {"dispatch": name, "wall_s": round(float(wall_s), 6),
                       "rounds": int(rounds), "compiles": int(compiles)})


# --- XLA compile counter ---------------------------------------------------
# Process-wide count of backend compiles, fed by jax.monitoring duration
# events ('/jax/core/compile/backend_compile_duration' fires once per
# non-cache-hit compile). Engines snapshot it around dispatches to expose
# a per-dispatch recompile delta; tests pin it to catch shape-instability
# regressions that would otherwise recompile silently every round.

_compile_counter: Dict[str, Any] = {"count": 0, "installed": False}
_COMPILE_EVENT = "/jax/core/compile/backend_compile_duration"


def install_compile_counter() -> None:
    """Idempotent: registers the jax.monitoring listener once per
    process. Safe to call before any jit runs."""
    if _compile_counter["installed"]:
        return
    try:
        import jax.monitoring as _jm

        def _on_event_duration(event: str, duration: float, **kw) -> None:
            if event == _COMPILE_EVENT:
                _compile_counter["count"] += 1

        _jm.register_event_duration_secs_listener(_on_event_duration)
        _compile_counter["installed"] = True
    except Exception as e:  # pragma: no cover - jax without monitoring
        logger.warning("compile counter unavailable (%s); dispatch "
                       "records will report compiles=0", e)
        _compile_counter["installed"] = True  # don't retry every round


def compile_count() -> int:
    """Backend compiles observed so far in this process (0 until
    :func:`install_compile_counter` has run)."""
    return int(_compile_counter["count"])


def log_training_status(status: str, run_id: Optional[str] = None) -> None:
    _emit("status", {"role": "client", "status": status})


def log_aggregation_status(status: str, run_id: Optional[str] = None) -> None:
    _emit("status", {"role": "server", "status": status})


def log_model_info(round_idx: int, model_path: str) -> None:
    _emit("model", {"round_idx": round_idx, "path": model_path})


def log_health(component: str, status: str,
               detail: Optional[Dict[str, Any]] = None) -> None:
    """One component health transition: watchdog trips (``stalled`` /
    ``nan_logits``), serving ``/healthz`` state changes. Post-mortems
    grep these to bracket when a process went bad."""
    _emit("health", {"component": str(component), "status": str(status),
                     "detail": detail})


# --- event spans (reference MLOpsProfilerEvent) ----------------------------

class event:
    """Span context manager / pair API — now a SHIM over the real tracer
    (``core/obs/trace``):

        with mlops.event("train", round_idx=3): ...
    or  mlops.event("train", started=True); ...; mlops.event("train",
        started=False)

    The old implementation kept a class-level ``{name: start_time}`` dict,
    so two concurrent same-name spans (cross-silo server handler threads,
    the async pour timer racing an upload thread) clobbered each other's
    start times and one duration came out garbage. Every event is now a
    real tracer span with its own handle: the context-manager form holds
    the span on the instance (no shared state at all), and the pair form
    keeps per-``(thread, name)`` LIFO stacks under a lock — an end pops
    the SAME thread's innermost open span of that name (cross-thread
    closes fall back to any-thread LIFO, for the rare legacy caller that
    splits a pair across threads). The legacy ``event_start``/
    ``event_end`` records still flow for old readers; the span record
    carries the trace-grade truth."""

    _open_lock = threading.Lock()
    # (thread_id, name) -> stack of open spans; None key = cross-thread
    # fallback pool per name
    _open: Dict[Any, list] = {}

    def __init__(self, name: str, started: Optional[bool] = None,
                 value: Any = None, **extra: Any):
        self.name = name
        self.extra = extra
        self._span = None
        if started is True:
            sp = obs_trace.tracer.start_span(name, attrs=dict(extra))
            with event._open_lock:
                event._open.setdefault(
                    (threading.get_ident(), name), []).append(
                    (sp, time.time()))
            _emit("event_start", {"event": name, "value": value, **extra})
        elif started is False:
            handle = self._pop_open(name)
            dur = None
            if handle is not None:
                sp, t0 = handle
                sp.end()
                # duration from the shim's own clock, so it survives
                # obs_tracing: false (the span is a no-op then)
                dur = time.time() - t0
            _emit("event_end", {"event": name, "value": value,
                                "duration_s": dur, **extra})

    @classmethod
    def _pop_open(cls, name: str):
        tid = threading.get_ident()
        with cls._open_lock:
            stack = cls._open.get((tid, name))
            if not stack:
                # legacy cross-thread pair: any thread's innermost span
                for key in reversed(list(cls._open)):
                    if key[1] == name and cls._open[key]:
                        stack = cls._open[key]
                        break
            if not stack:
                return None
            sp = stack.pop()
            if not stack:
                cls._open = {k: v for k, v in cls._open.items() if v}
            return sp

    def __enter__(self):
        self._span = obs_trace.tracer.start_span(self.name,
                                                 attrs=dict(self.extra))
        self._span.__enter__()
        self._t0 = time.time()
        _emit("event_start", {"event": self.name, **self.extra})
        return self

    def __exit__(self, *exc):
        dur = time.time() - self._t0
        self._span.__exit__(*exc)
        _emit("event_end", {"event": self.name, "duration_s": dur,
                            **self.extra})
        return False


# --- system perf daemon (reference mlops_device_perfs.py) ------------------

_sys_perf_state = {"psutil_warned": False, "sample_warned": False}


def _sys_sample() -> Dict[str, Any]:
    """One host+device sample. psutil is OPTIONAL: an environment without
    it used to kill the sampler thread with an unlogged ImportError on the
    very first sample — now the host-side fields degrade away ONCE,
    loudly, and the jax-only device stats keep flowing."""
    rec: Dict[str, Any] = {}
    try:
        import psutil
        vm = psutil.virtual_memory()
        rec.update({"cpu_pct": psutil.cpu_percent(interval=None),
                    "mem_pct": vm.percent,
                    "mem_used_gb": round(vm.used / 2**30, 3)})
    except Exception as e:
        if not _sys_perf_state["psutil_warned"]:
            _sys_perf_state["psutil_warned"] = True
            logger.warning(
                "sys_perf: psutil unavailable (%s: %s) — degrading to "
                "jax-only device stats", type(e).__name__, e)
        rec["degraded"] = True
    try:
        import jax
        stats = jax.local_devices()[0].memory_stats() or {}
        if "bytes_in_use" in stats:
            rec["device_mem_gb"] = round(stats["bytes_in_use"] / 2**30, 3)
    except Exception:
        pass
    return rec


def start_sys_perf(interval_s: float = 10.0) -> None:
    if _state.get("sys_thread"):
        return

    def loop():
        # identity check: a stop+start within one interval must not leave
        # the old thread alive emitting duplicates
        while _state.get("sys_thread") is threading.current_thread():
            try:
                _emit("sys_perf", _sys_sample())
            except Exception:
                # the sampler must never die silently: one WARNING with
                # the traceback, then keep sampling (a transient device
                # query failure is not a reason to go dark for the run)
                if not _sys_perf_state["sample_warned"]:
                    _sys_perf_state["sample_warned"] = True
                    logger.warning("sys_perf sample failed; sampler "
                                   "continues", exc_info=True)
            time.sleep(interval_s)

    t = threading.Thread(target=loop, daemon=True)
    _state["sys_thread"] = t
    t.start()


def stop_sys_perf() -> None:
    _state["sys_thread"] = None


# --- JAX profiler bridge ---------------------------------------------------

def start_device_trace(log_dir: Optional[str] = None) -> str:
    """Start a JAX/XLA profiler trace (TensorBoard-viewable) — the
    TPU-native replacement for wall-clock profiling."""
    import jax
    path = os.path.expanduser(log_dir or "~/.cache/fedml_tpu/traces")
    os.makedirs(path, exist_ok=True)
    jax.profiler.start_trace(path)
    return path


def stop_device_trace() -> None:
    import jax
    jax.profiler.stop_trace()
