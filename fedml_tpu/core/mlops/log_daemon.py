"""Runtime log shipper — tails per-run log files and POSTs batches to a
log server.

Parity target: reference ``core/mlops/mlops_runtime_log_daemon.py`` —
``log_process`` tails the run's log file from a persisted line index,
batches up to ``log_line_chunk_size`` lines, POSTs
``{run_id, edge_id, logs_list}`` to the platform endpoint with bounded
retries (:219 ``log_upload``, :333 the tail loop), and survives file
rotation. This is the same machine over stdlib ``urllib`` with the repo's
local-first defaults: the endpoint is any HTTP sink (``log_server_url``),
and the shipped file is the run's JSONL metric/event log or a run
registry's stdout log.

Rotation-awareness: the tail keeps (inode, offset); when the file is
rotated (inode change) or truncated (size < offset) it reopens from the
start of the new file instead of silently stopping (reference handles
this by re-reading the index each cycle).
"""

from __future__ import annotations

import atexit
import json
import logging
import os
import threading
import time
import urllib.error
import urllib.request
from typing import List, Optional

logger = logging.getLogger(__name__)


class LogShipper:
    """Tail ``path`` and POST line batches to ``url`` until stopped."""

    def __init__(self, path: str, url: str, run_id: str = "0",
                 device_id: str = "0", batch_lines: int = 100,
                 interval_s: float = 1.0, retries: int = 3,
                 timeout_s: float = 5.0):
        self.path = path
        self.url = url
        self.run_id = str(run_id)
        self.device_id = str(device_id)
        self.batch_lines = int(batch_lines)
        self.interval_s = float(interval_s)
        self.retries = int(retries)
        self.timeout_s = float(timeout_s)
        self._seq = 0
        self._offset = 0  # BYTE offset (file is read in binary mode: a
        # text-mode tell() is an opaque cookie that need not equal byte
        # counts on non-UTF-8 logs, which would desync the st_size
        # truncation check)
        self._inode: Optional[int] = None
        self._buf = b""  # partial trailing line across reads (bytes, so
        # a multi-byte char split across reads survives intact)
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._flush_lock = threading.Lock()
        self._flushed = False
        self.shipped_lines = 0
        self.failed_batches = 0

    # -- tailing ------------------------------------------------------------

    def _read_new_lines(self) -> List[str]:
        try:
            st = os.stat(self.path)
        except OSError:
            return []
        if self._inode is not None and (st.st_ino != self._inode
                                        or st.st_size < self._offset):
            # rotated or truncated: start over on the new file
            logger.info("log shipper: %s rotated, re-tailing", self.path)
            self._offset = 0
            self._buf = b""
        self._inode = st.st_ino
        if st.st_size <= self._offset:
            return []
        with open(self.path, "rb") as f:
            f.seek(self._offset)
            raw = f.read()
            self._offset = f.tell()
        data = self._buf + raw
        # universal newlines by hand (binary mode): CR-only progress bars
        # (tqdm-style) and CRLF logs must still split into lines — buffering
        # until LF would hoard a \r-only stream forever
        import re as _re
        chunks = _re.split(b"\r\n|\r|\n", data)
        self._buf = chunks.pop()  # incomplete tail (or b"")
        return [ln for ln in
                (c.decode("utf-8", errors="replace") for c in chunks)
                if ln.strip()]

    # -- upload -------------------------------------------------------------

    def _post(self, lines: List[str]) -> bool:
        body = json.dumps({
            "run_id": self.run_id, "device_id": self.device_id,
            "seq": self._seq, "log_lines": lines}).encode()
        req = urllib.request.Request(
            self.url, data=body,
            headers={"Content-Type": "application/json"}, method="POST")
        delay = 0.2
        for attempt in range(self.retries):
            try:
                with urllib.request.urlopen(req,
                                            timeout=self.timeout_s) as r:
                    if 200 <= r.status < 300:
                        self._seq += 1
                        self.shipped_lines += len(lines)
                        return True
            except (urllib.error.URLError, OSError) as e:
                logger.debug("log upload attempt %d failed: %s",
                             attempt + 1, e)
            if self._stop.wait(delay):
                break
            delay *= 2
        self.failed_batches += 1
        return False

    def pump_once(self) -> int:
        """One tail+ship cycle; returns lines shipped. Public so tests (and
        a final flush on stop) can drive it synchronously."""
        shipped = 0
        while True:
            lines = self._read_new_lines()
            if not lines:
                return shipped
            for i in range(0, len(lines), self.batch_lines):
                batch = lines[i:i + self.batch_lines]
                if self._post(batch):
                    shipped += len(batch)
                else:
                    return shipped  # retry same region next cycle? no —
                    # offset already advanced; dropping is the reference's
                    # behavior after its retries are exhausted

    # -- lifecycle ----------------------------------------------------------

    def start(self) -> "LogShipper":
        def loop():
            while not self._stop.wait(self.interval_s):
                self.pump_once()
            self._final_flush()

        self._thread = threading.Thread(target=loop, daemon=True)
        self._thread.start()
        # interpreter-exit flush: a short run can finish inside the first
        # poll interval and previously lost its entire tail (daemon
        # threads are killed, not joined, at exit) — the atexit hook
        # ships whatever is still unsent. Unregistered on stop().
        atexit.register(self._atexit_stop)
        return self

    def _atexit_stop(self) -> None:
        self.stop(flush=True, timeout_s=5.0)

    def _final_flush(self) -> None:
        """Ship everything, INCLUDING a trailing line with no newline — a
        crashed job's log usually ends mid-line and that last partial
        traceback line is the most diagnostic one. Runs at most once
        (the loop thread's exit path, ``stop()``, and the atexit hook
        can all race here)."""
        with self._flush_lock:
            if self._flushed:
                return
            self._flushed = True
            self.pump_once()
            tail = self._buf.decode("utf-8", errors="replace")
            if tail.strip():
                if self._post([tail]):
                    self._buf = b""

    def stop(self, flush: bool = True, timeout_s: float = 10.0) -> None:
        self._stop.set()
        t, self._thread = self._thread, None
        if t is not None:
            t.join(timeout=timeout_s)
            if t.is_alive():
                # the loop thread is stuck mid-POST past our patience: it
                # still owns _buf/_offset and will run its OWN final
                # flush when the socket call returns — flushing from here
                # too would race pump_once over unsynchronized tail state
                logger.warning(
                    "log shipper: loop thread still sending after %.1fs; "
                    "it will flush on its own exit", timeout_s)
                flush = False
        if flush:
            # guaranteed final flush even when the loop thread never ran
            # a cycle (short run) or was never started — _final_flush
            # itself dedups against the loop thread's exit-path flush
            self._final_flush()
        try:
            atexit.unregister(self._atexit_stop)
        except Exception:
            pass


_shippers: List[LogShipper] = []


def start_log_shipper(path: str, url: str, run_id: str = "0",
                      device_id: str = "0", **kw) -> LogShipper:
    """Module-level registry so ``mlops.init`` / the run registry can start
    shippers and tests can flush them."""
    s = LogShipper(path, url, run_id=run_id, device_id=device_id,
                   **kw).start()
    _shippers.append(s)
    return s


def stop_all_shippers() -> None:
    for s in _shippers:
        s.stop()
    _shippers.clear()
