"""Chaos subsystem: deterministic fault injection + fault-tolerance seams.

``FaultPlan`` is the seeded schedule (dropout / stragglers / link faults /
crash-at-round), ``ChaosCommManager`` the transport interceptor, and
``FaultLedger`` the injected-vs-observed accounting mirrored to mlops.
Everything is OFF by default — with the ``chaos_*`` knobs at their
defaults the simulator programs and the cross-silo wire are unchanged.
"""

from .interceptor import ChaosCommManager
from .plan import (ChaosCrash, FaultLedger, FaultPlan, LinkDecision,
                   RoundFaults)
from .serving import ServingChaosInjector

__all__ = ["ChaosCommManager", "ChaosCrash", "FaultLedger", "FaultPlan",
           "LinkDecision", "RoundFaults", "ServingChaosInjector"]
