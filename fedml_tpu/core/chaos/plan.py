"""Deterministic fault injection — the chaos subsystem's schedule.

Real federated deployments (the reference's Octopus/Beehive pillars) live
with client dropout, stragglers, flaky links, and mid-run crashes; the
literature treats partial participation and straggler tolerance as
first-class (FedAvg's client sampling, FedProx-style partial local work).
A robustness claim that cannot be *tested* is a hope, not a property — so
every fault here is drawn from a seeded, stateless schedule: the same
``chaos_seed`` reproduces the same dropout/straggler/crash trace in any
process, in any order of queries, which is what makes crash-resume and
tolerance tests assertable instead of flaky.

Statelessness is the load-bearing design decision: each decision is a pure
function of ``(seed, kind, round_idx, client_id)`` via a fresh
``np.random.Generator`` seeded with that tuple (SeedSequence hashing is
platform-stable). Server and client processes holding the same args agree
on the plan without any coordination, and the injected-vs-observed ledger
can be reconciled after the fact.
"""

from __future__ import annotations

import logging
import threading
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

logger = logging.getLogger(__name__)

# domain-separation tags for the per-decision PRNG streams (arbitrary
# distinct ints; folded into the SeedSequence entropy tuple)
_TAG_DROP = 11
_TAG_STRAGGLE = 13
_TAG_LINK = 17
_TAG_SERVE_STEP = 19    # per-decode-step engine faults (stall / NaN)
_TAG_SERVE_GW = 23      # per-request gateway->replica connection drops


def _none_or_int(v: Any) -> Optional[int]:
    # NOT `v in (None, "", False)`: 0 == False in Python, and step/request
    # index 0 is a legal fault position (crash on the FIRST request)
    if v is None or v == "" or v is False:
        return None
    return int(v)


class ChaosCrash(RuntimeError):
    """Injected crash-at-round event. Raised by the engine AFTER the round
    (and its checkpoint, when due) completes — the crash-resume e2e path:
    catch it, re-run, and the ``RoundCheckpointer`` restores the trajectory.
    """

    def __init__(self, round_idx: int):
        super().__init__(f"chaos: injected crash at round {round_idx}")
        self.round_idx = int(round_idx)


@dataclass(frozen=True)
class RoundFaults:
    """The plan's verdict for one round over a candidate client set."""

    round_idx: int
    dropped: Tuple[int, ...]                 # client ids that never report
    work_scale: Dict[int, float] = field(default_factory=dict)
    # client id -> fraction of local work a straggler completes (absent =
    # full work; dropped clients are NOT also listed as stragglers)

    def scale_for(self, client_id: int) -> float:
        if client_id in self.dropped:
            return 0.0
        return float(self.work_scale.get(client_id, 1.0))


@dataclass(frozen=True)
class LinkDecision:
    """Fault verdict for one message on a link: how many copies to deliver
    (0 = loss, 2 = duplication) after an optional delay."""

    copies: int = 1
    delay_s: float = 0.0

    @property
    def faulty(self) -> bool:
        return self.copies != 1 or self.delay_s > 0.0


class FaultPlan:
    """Seeded schedule of per-round client dropouts, straggler slowdowns
    (reduced local-step fractions), link loss/duplication/delay, and
    crash-at-round events. All knobs default to OFF: a default-constructed
    plan is ``enabled == False`` and injects nothing."""

    def __init__(self, seed: int = 0, dropout_prob: float = 0.0,
                 straggler_prob: float = 0.0, straggler_work: float = 0.5,
                 link_loss_prob: float = 0.0, link_dup_prob: float = 0.0,
                 link_delay_prob: float = 0.0, link_delay_s: float = 0.0,
                 crash_at_round: Optional[int] = None,
                 serving_stall_prob: float = 0.0,
                 serving_stall_s: float = 0.0,
                 serving_stall_at_step: Optional[int] = None,
                 serving_nan_prob: float = 0.0,
                 serving_nan_at_step: Optional[int] = None,
                 serving_conn_drop_prob: float = 0.0,
                 serving_crash_at_request: Optional[int] = None):
        def _opt(v):
            return None if v is None or int(v) < 0 else int(v)

        self.seed = int(seed)
        self.dropout_prob = float(dropout_prob)
        self.straggler_prob = float(straggler_prob)
        self.straggler_work = min(max(float(straggler_work), 0.0), 1.0)
        self.link_loss_prob = float(link_loss_prob)
        self.link_dup_prob = float(link_dup_prob)
        self.link_delay_prob = float(link_delay_prob)
        self.link_delay_s = max(float(link_delay_s), 0.0)
        self.crash_at_round = _opt(crash_at_round)
        # serving fault kinds (the serving plane's analogue of link
        # faults): injected decode stalls, NaN-logit poison, gateway->
        # replica connection drops, and replica crash-at-request-N. Every
        # decision is a pure function of (seed, kind, index); the *_at_*
        # forms are the deterministic single-shot variants tests pin.
        self.serving_stall_prob = float(serving_stall_prob)
        self.serving_stall_s = max(float(serving_stall_s), 0.0)
        self.serving_stall_at_step = _opt(serving_stall_at_step)
        self.serving_nan_prob = float(serving_nan_prob)
        self.serving_nan_at_step = _opt(serving_nan_at_step)
        self.serving_conn_drop_prob = float(serving_conn_drop_prob)
        self.serving_crash_at_request = _opt(serving_crash_at_request)

    @classmethod
    def from_args(cls, args) -> "FaultPlan":
        """Build from the ``chaos_*`` knobs in ``arguments.py`` (all off by
        default). ``chaos_seed`` falls back to ``random_seed`` so a seeded
        run's faults are reproducible without an extra knob."""
        seed = getattr(args, "chaos_seed", None)
        if seed is None:
            seed = getattr(args, "random_seed", 0)
        return cls(
            seed=int(seed),
            dropout_prob=float(getattr(args, "chaos_dropout_prob", 0.0)
                               or 0.0),
            straggler_prob=float(getattr(args, "chaos_straggler_prob", 0.0)
                                 or 0.0),
            straggler_work=float(getattr(args, "chaos_straggler_work", 0.5)
                                 or 0.5),
            link_loss_prob=float(getattr(args, "chaos_link_loss_prob", 0.0)
                                 or 0.0),
            link_dup_prob=float(getattr(args, "chaos_link_dup_prob", 0.0)
                                or 0.0),
            link_delay_prob=float(getattr(args, "chaos_link_delay_prob", 0.0)
                                  or 0.0),
            link_delay_s=float(getattr(args, "chaos_link_delay_s", 0.0)
                               or 0.0),
            crash_at_round=_none_or_int(
                getattr(args, "chaos_crash_at_round", None)),
            serving_stall_prob=float(
                getattr(args, "chaos_serving_stall_prob", 0.0) or 0.0),
            serving_stall_s=float(
                getattr(args, "chaos_serving_stall_s", 0.0) or 0.0),
            serving_stall_at_step=_none_or_int(
                getattr(args, "chaos_serving_stall_at_step", None)),
            serving_nan_prob=float(
                getattr(args, "chaos_serving_nan_prob", 0.0) or 0.0),
            serving_nan_at_step=_none_or_int(
                getattr(args, "chaos_serving_nan_at_step", None)),
            serving_conn_drop_prob=float(
                getattr(args, "chaos_serving_conn_drop_prob", 0.0) or 0.0),
            serving_crash_at_request=_none_or_int(
                getattr(args, "chaos_serving_crash_at_request", None)),
        )

    # --- enablement ---------------------------------------------------------
    @property
    def injects_availability(self) -> bool:
        return self.dropout_prob > 0.0 or self.straggler_prob > 0.0

    @property
    def expected_work_fraction(self) -> float:
        """Mean fraction of its SCHEDULED local work a client actually runs
        under the availability knobs: dropped clients run 0, stragglers
        ``straggler_work``, the rest 1.0. This is what the bench's FLOPs
        costing must scale by — counting full epochs for clients the plan
        drops would overstate MFU under injection."""
        alive = 1.0 - min(max(self.dropout_prob, 0.0), 1.0)
        p_s = min(max(self.straggler_prob, 0.0), 1.0)
        return alive * (1.0 - p_s + p_s * self.straggler_work)

    @property
    def injects_link_faults(self) -> bool:
        return (self.link_loss_prob > 0.0 or self.link_dup_prob > 0.0
                or (self.link_delay_prob > 0.0 and self.link_delay_s > 0.0))

    @property
    def injects_serving_faults(self) -> bool:
        return ((self.serving_stall_prob > 0.0
                 or self.serving_stall_at_step is not None)
                and self.serving_stall_s > 0.0) \
            or self.serving_nan_prob > 0.0 \
            or self.serving_nan_at_step is not None \
            or self.serving_conn_drop_prob > 0.0 \
            or self.serving_crash_at_request is not None

    @property
    def enabled(self) -> bool:
        return (self.injects_availability or self.injects_link_faults
                or self.injects_serving_faults
                or self.crash_at_round is not None)

    # --- per-decision PRNG --------------------------------------------------
    def _rng(self, tag: int, *key: int) -> np.random.Generator:
        # one fresh Generator per decision: stateless, order-independent,
        # identical across processes holding the same seed
        return np.random.default_rng((self.seed, tag) + tuple(
            int(k) & 0x7FFFFFFF for k in key))

    # --- availability faults ------------------------------------------------
    def is_dropped(self, round_idx: int, client_id: int) -> bool:
        if self.dropout_prob <= 0.0:
            return False
        u = self._rng(_TAG_DROP, round_idx, client_id).random()
        return bool(u < self.dropout_prob)

    def work_scale(self, round_idx: int, client_id: int) -> float:
        """Fraction of its local work this client completes this round:
        0.0 = dropped, ``straggler_work`` = straggler, 1.0 = healthy."""
        if self.is_dropped(round_idx, client_id):
            return 0.0
        if self.straggler_prob <= 0.0:
            return 1.0
        u = self._rng(_TAG_STRAGGLE, round_idx, client_id).random()
        return self.straggler_work if u < self.straggler_prob else 1.0

    def round_faults(self, round_idx: int,
                     client_ids: Sequence[int]) -> RoundFaults:
        dropped: List[int] = []
        scales: Dict[int, float] = {}
        for cid in client_ids:
            if self.is_dropped(round_idx, cid):
                dropped.append(int(cid))
                continue
            s = self.work_scale(round_idx, cid)
            if s < 1.0:
                scales[int(cid)] = s
        return RoundFaults(round_idx=int(round_idx),
                           dropped=tuple(dropped), work_scale=scales)

    def trace(self, n_rounds: int,
              client_ids: Sequence[int]) -> List[RoundFaults]:
        """The full deterministic fault trace — what tests assert
        reproduces under the same seed."""
        return [self.round_faults(r, client_ids) for r in range(n_rounds)]

    # --- link faults --------------------------------------------------------
    def link_decision(self, sender: int, receiver: int,
                      seq: int) -> LinkDecision:
        """Fault verdict for the ``seq``-th message this process sends on
        the (sender, receiver) link. Seeded per (link, seq): a rerun with
        the same send order replays the same loss/dup/delay pattern."""
        if not self.injects_link_faults:
            return LinkDecision()
        rng = self._rng(_TAG_LINK, sender, receiver, seq)
        u_loss, u_dup, u_delay = rng.random(3)
        copies = 1
        if self.link_loss_prob > 0.0 and u_loss < self.link_loss_prob:
            copies = 0
        elif self.link_dup_prob > 0.0 and u_dup < self.link_dup_prob:
            copies = 2
        delay = 0.0
        if (copies > 0 and self.link_delay_prob > 0.0
                and self.link_delay_s > 0.0
                and u_delay < self.link_delay_prob):
            delay = self.link_delay_s
        return LinkDecision(copies=copies, delay_s=delay)

    # --- serving faults -----------------------------------------------------
    def serving_decode_fault(self, step_idx: int) -> Optional[str]:
        """Fault verdict for the engine's ``step_idx``-th decode step:
        ``"nan"`` (poisoned logits), ``"stall"`` (the step wedges for
        ``serving_stall_s``), or None. Pure function of (seed, kind,
        step_idx): the same plan replays the same fault trace after any
        engine reset — which is what makes recovery determinism a test
        instead of a hope. NaN wins a tie (a poisoned step is the louder
        failure)."""
        step_idx = int(step_idx)
        if self.serving_nan_at_step is not None \
                and step_idx == self.serving_nan_at_step:
            return "nan"
        if self.serving_stall_at_step is not None \
                and step_idx == self.serving_stall_at_step \
                and self.serving_stall_s > 0.0:
            return "stall"
        if self.serving_nan_prob <= 0.0 and (
                self.serving_stall_prob <= 0.0
                or self.serving_stall_s <= 0.0):
            return None
        u_nan, u_stall = self._rng(_TAG_SERVE_STEP, step_idx).random(2)
        if self.serving_nan_prob > 0.0 and u_nan < self.serving_nan_prob:
            return "nan"
        if (self.serving_stall_prob > 0.0 and self.serving_stall_s > 0.0
                and u_stall < self.serving_stall_prob):
            return "stall"
        return None

    def gateway_drop(self, seq: int) -> bool:
        """True when the ``seq``-th gateway request should see its
        replica connection dropped before any byte reaches a predictor
        (the WAN-flake analogue for the serving wire)."""
        if self.serving_conn_drop_prob <= 0.0:
            return False
        u = self._rng(_TAG_SERVE_GW, seq).random()
        return bool(u < self.serving_conn_drop_prob)

    def serving_crash_due(self, request_idx: int) -> bool:
        """True when the replica should crash on its ``request_idx``-th
        served request (0-based) — the container-kill analogue."""
        return (self.serving_crash_at_request is not None
                and int(request_idx) == self.serving_crash_at_request)

    # --- crash events -------------------------------------------------------
    def crash_due(self, round_idx: int) -> bool:
        return (self.crash_at_round is not None
                and int(round_idx) == self.crash_at_round)

    def __repr__(self) -> str:
        return (f"FaultPlan(seed={self.seed}, drop={self.dropout_prob}, "
                f"straggle={self.straggler_prob}@{self.straggler_work}, "
                f"link=({self.link_loss_prob},{self.link_dup_prob},"
                f"{self.link_delay_prob}x{self.link_delay_s}s), "
                f"crash_at={self.crash_at_round}, "
                f"serving=(stall={self.serving_stall_prob}"
                f"@{self.serving_stall_at_step}x{self.serving_stall_s}s,"
                f"nan={self.serving_nan_prob}@{self.serving_nan_at_step},"
                f"drop={self.serving_conn_drop_prob},"
                f"crash_req={self.serving_crash_at_request}))")


class FaultLedger:
    """Injected-vs-observed fault accounting, one record per round (plus
    link events), mirrored to the mlops sink. ``injected`` is what the
    :class:`FaultPlan` scheduled; ``observed`` is what the runtime actually
    saw at the aggregation seam — a tolerance bug shows up as the two
    disagreeing."""

    def __init__(self):
        self._lock = threading.Lock()
        self._rounds: List[Dict[str, Any]] = []
        self._links: List[Dict[str, Any]] = []
        self._serving: List[Dict[str, Any]] = []

    def record_round(self, round_idx: int, injected: Dict[str, Any],
                     observed: Dict[str, Any]) -> None:
        rec = {"round_idx": int(round_idx), "injected": injected,
               "observed": observed}
        with self._lock:
            self._rounds.append(rec)
        from .. import mlops
        mlops.log_chaos(round_idx=int(round_idx), injected=injected,
                        observed=observed)

    def record_pour(self, version: int, arrivals: List[Dict[str, Any]],
                    observed: Dict[str, Any]) -> None:
        """One buffered-async pour: the per-update arrival records
        (client, staleness at aggregation, arrival timestamp, dispatch
        version) plus what the pour observed (count, leftover buffer,
        staleness cap in force). This is what lets the bench and
        post-mortems reconstruct the arrival distribution — and what the
        soak test balances against the buffer's add/pour counters."""
        rec = {"round_idx": int(version), "pour": True,
               "injected": {"arrivals": list(arrivals)},
               "observed": dict(observed)}
        with self._lock:
            self._rounds.append(rec)
        from .. import mlops
        mlops.log_chaos(round_idx=int(version),
                        arrivals=list(arrivals),
                        observed=dict(observed))

    def pours(self) -> List[Dict[str, Any]]:
        with self._lock:
            return [r for r in self._rounds if r.get("pour")]

    def record_link(self, sender: int, receiver: int, msg_type: Any,
                    decision: LinkDecision) -> None:
        rec = {"sender": int(sender), "receiver": int(receiver),
               "msg_type": str(msg_type), "copies": decision.copies,
               "delay_s": decision.delay_s}
        with self._lock:
            self._links.append(rec)
        from .. import mlops
        mlops.log_chaos(link=rec)

    def record_serving(self, kind: str, **detail: Any) -> None:
        """One injected serving fault (stall / nan / conn_drop / crash)
        with whatever locates it (step_idx, seq, request_idx). The soak
        test balances these against the engine's observed recoveries —
        an injected fault with no matching reset/failover is a tolerance
        bug."""
        rec = {"kind": str(kind), **detail}
        with self._lock:
            self._serving.append(rec)
        from .. import mlops
        mlops.log_chaos(serving=rec)

    def rounds(self) -> List[Dict[str, Any]]:
        with self._lock:
            return list(self._rounds)

    def links(self) -> List[Dict[str, Any]]:
        with self._lock:
            return list(self._links)

    def serving_events(self) -> List[Dict[str, Any]]:
        with self._lock:
            return list(self._serving)

    def snapshot(self) -> Dict[str, Any]:
        with self._lock:
            return {"rounds": list(self._rounds), "links": list(self._links),
                    "serving": list(self._serving)}
