"""Serving-plane chaos: the engine/gateway/replica fault interceptors.

The FL side injects faults at the transport seam (``ChaosCommManager``);
the serving plane's seams are different — the decode loop, the gateway's
connect, and the replica process itself — so this module adapts the same
seeded :class:`FaultPlan` to them. One :class:`ServingChaosInjector`
instance per process holds the plan plus the tiny bit of state the pure
decisions need (which request index this is); every *decision* stays a
pure function of ``(chaos_seed, kind, index)``, so a rerun with the same
plan replays the same fault trace — which is what lets the soak test
assert "every injected fault was recovered from" instead of hoping.

All knobs are OFF by default: a default-constructed plan injects nothing
and the engine/gateway never consult an injector at all (``from_args``
returns None when no serving knob is set).
"""

from __future__ import annotations

import logging
import threading
from typing import Any, Dict, Optional

from .plan import FaultLedger, FaultPlan

logger = logging.getLogger(__name__)


class ServingChaosInjector:
    """Per-process serving fault interceptor over one seeded plan.

    * ``decode_fault(step_idx)`` — the engine consults it before each
      decode step: ``"nan"`` poisons the step's logits flag, ``"stall"``
      wedges the loop for ``stall_s()`` seconds (interruptibly, so the
      watchdog-driven reset can cut the stall short exactly like a
      process restart would);
    * ``connection_drop()`` — the gateway consults it per outgoing
      request; True simulates a refused/reset connect before any byte
      reaches the replica;
    * ``request_crash_due()`` — the replica's HTTP runner consults it per
      served request; with ``hard_crash`` the replica process exits
      (subprocess replicas only), otherwise the connection is severed
      mid-request (the in-process analogue).

    Every injected fault is recorded in the :class:`FaultLedger` so the
    injected-vs-observed reconciliation covers the serving plane too.
    """

    def __init__(self, plan: FaultPlan,
                 ledger: Optional[FaultLedger] = None,
                 hard_crash: bool = False):
        self.plan = plan
        self.ledger = ledger if ledger is not None else FaultLedger()
        self.hard_crash = bool(hard_crash)
        self._lock = threading.Lock()
        self._gw_seq = 0
        self._req_seq = 0

    @classmethod
    def from_args(cls, args,
                  ledger: Optional[FaultLedger] = None,
                  hard_crash: bool = False
                  ) -> Optional["ServingChaosInjector"]:
        """An injector when any ``chaos_serving_*`` knob is live, else
        None — the default path never pays a per-step plan consult."""
        plan = FaultPlan.from_args(args)
        if not plan.injects_serving_faults:
            return None
        return cls(plan, ledger=ledger, hard_crash=hard_crash)

    # ------------------------------------------------------------ engine --
    def decode_fault(self, step_idx: int) -> Optional[str]:
        kind = self.plan.serving_decode_fault(step_idx)
        if kind is not None:
            self.ledger.record_serving(kind, step_idx=int(step_idx))
        return kind

    def stall_s(self) -> float:
        return self.plan.serving_stall_s

    # ----------------------------------------------------------- gateway --
    def connection_drop(self) -> bool:
        """Per-request verdict; the request index is this process's send
        counter, so the drop pattern is fixed for a given send order."""
        with self._lock:
            seq = self._gw_seq
            self._gw_seq += 1
        if self.plan.gateway_drop(seq):
            self.ledger.record_serving("conn_drop", seq=seq)
            return True
        return False

    # ----------------------------------------------------------- replica --
    def request_crash_due(self) -> bool:
        """Counts served requests; True exactly on request N of the
        plan's crash-at-request-N."""
        with self._lock:
            idx = self._req_seq
            self._req_seq += 1
        if self.plan.serving_crash_due(idx):
            self.ledger.record_serving("replica_crash", request_idx=idx,
                                       hard=self.hard_crash)
            return True
        return False

    def snapshot(self) -> Dict[str, Any]:
        with self._lock:
            return {"gateway_seq": self._gw_seq,
                    "request_seq": self._req_seq,
                    "injected": self.ledger.serving_events()}
