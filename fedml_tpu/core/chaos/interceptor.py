"""Chaos interceptor at the ``Message`` send seam.

Wraps any :class:`BaseCommunicationManager` and consults the
:class:`FaultPlan` per outgoing message: deliver 0 copies (link loss),
2 copies (duplication), or the usual 1, optionally after a delay —
exercising exactly the failure modes a WAN inflicts on the FSMs without
touching any transport. Receive-side behavior is delegated untouched, so
an interceptor-wrapped manager is byte-identical on the wire for every
message the plan leaves alone (and absent link-fault knobs the manager is
never wrapped at all — the default path does not change)."""

from __future__ import annotations

import logging
import threading
from typing import Optional

from ..distributed.communication.base_com_manager import (
    BaseCommunicationManager, Observer)
from ..distributed.communication.message import Message
from .plan import FaultLedger, FaultPlan

logger = logging.getLogger(__name__)


class ChaosCommManager(BaseCommunicationManager):
    """Decorator transport: every ``send_message`` passes through the
    fault plan; everything else forwards to the wrapped manager."""

    def __init__(self, inner: BaseCommunicationManager, plan: FaultPlan,
                 rank: int, ledger: Optional[FaultLedger] = None):
        super().__init__()
        self.inner = inner
        self.plan = plan
        self.rank = int(rank)
        self.ledger = ledger if ledger is not None else FaultLedger()
        self._seq_lock = threading.Lock()
        self._seq: dict = {}   # receiver -> messages sent on that link

    def _next_seq(self, receiver: int) -> int:
        with self._seq_lock:
            n = self._seq.get(receiver, 0)
            self._seq[receiver] = n + 1
            return n

    # --- fault-injecting send ----------------------------------------------
    def send_message(self, msg: Message) -> None:
        receiver = msg.get_receiver_id()
        seq = self._next_seq(receiver)
        decision = self.plan.link_decision(self.rank, receiver, seq)
        if decision.faulty:
            self.ledger.record_link(self.rank, receiver, msg.get_type(),
                                    decision)
            # trace-plane mirror of the ledger entry: the fault lands as
            # an event on whatever span is sending (broadcast, upload),
            # so a dropped/delayed message is visible ON the round's
            # trace instead of only in a separate ledger
            from ..obs import trace as obs_trace
            obs_trace.add_event(
                "chaos.link_fault", link=f"{self.rank}->{receiver}",
                msg_type=str(msg.get_type()), copies=int(decision.copies),
                delay_s=float(decision.delay_s))
        if decision.copies <= 0:
            logger.warning("chaos: dropping message %r on link %d->%s",
                           msg.get_type(), self.rank, receiver)
            return
        if decision.delay_s > 0.0:
            # deliver later from a timer thread — out-of-order arrival is
            # part of the injected fault, exactly like a slow WAN hop
            t = threading.Timer(decision.delay_s, self._deliver,
                                args=(msg, decision.copies))
            t.daemon = True
            t.start()
            return
        # the plain path keeps the wrapped transport's failure surface
        # (retry exhaustion must still raise to the caller); only the
        # injected EXTRA copy downgrades failures to a log line
        self.inner.send_message(msg)
        if decision.copies > 1:
            self._deliver(msg, decision.copies - 1)

    def _deliver(self, msg: Message, copies: int) -> None:
        """Timer-thread / duplicate deliveries: raising here would kill
        nothing useful — log and move on."""
        for _ in range(copies):
            try:
                self.inner.send_message(msg)
            except Exception:
                logger.exception("chaos: delayed/dup delivery failed "
                                 "(link %d->%s)", self.rank,
                                 msg.get_receiver_id())

    # --- delegation ---------------------------------------------------------
    def add_observer(self, observer: Observer) -> None:
        self.inner.add_observer(observer)

    def remove_observer(self, observer: Observer) -> None:
        self.inner.remove_observer(observer)

    def notify(self, msg: Message) -> None:
        self.inner.notify(msg)

    def handle_receive_message(self) -> None:
        self.inner.handle_receive_message()

    def stop_receive_message(self) -> None:
        self.inner.stop_receive_message()
